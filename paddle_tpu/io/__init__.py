"""Data pipeline (parity: python/paddle/io/ — Dataset, IterableDataset,
DataLoader with multiprocess workers, BatchSampler,
DistributedBatchSampler).

TPU-native notes: the reference's pinned-memory + CUDA-stream H2D
machinery is replaced by async ``jax.device_put`` with a double-buffered
prefetch (``prefetch_to_device``) so the host never gates the step loop.
Worker processes use the standard multiprocessing pool; the per-step hot
path stays numpy until the final device_put.
"""

from __future__ import annotations

import itertools
import math
import queue
import threading
from typing import Callable, Iterable, Iterator, List, Optional, Sequence

import jax
import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise TypeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, *tensors):
        # paddle's signature is TensorDataset(tensors) — one LIST of
        # arrays (python/paddle/io/dataloader/dataset.py); the starred
        # torch spelling is accepted too since both are common in
        # migrating code
        if len(tensors) == 1 and isinstance(tensors[0], (list, tuple)):
            tensors = tuple(tensors[0])
        self.tensors = [np.asarray(t) for t in tensors]
        assert all(len(t) == len(self.tensors[0]) for t in self.tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator_seed: int = 0):
    total = len(dataset)
    assert sum(lengths) == total
    perm = np.random.default_rng(generator_seed).permutation(total)
    out, start = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[start:start + n].tolist()))
        start += n
    return out


class ComposeDataset(Dataset):
    """Parity: paddle.io.ComposeDataset — zip same-length datasets into
    one whose samples are the concatenated fields."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert self.datasets, "ComposeDataset needs at least one dataset"
        n = len(self.datasets[0])
        assert all(len(d) == n for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (tuple, list)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ConcatDataset(Dataset):
    """Parity: paddle.io.ConcatDataset — datasets end-to-end."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        lo = int(np.searchsorted(self.cum, idx, side="right"))
        prev = self.cum[lo - 1] if lo else 0
        return self.datasets[lo][idx - prev]

    def __len__(self):
        return self.cum[-1] if self.cum else 0


class ChainDataset(IterableDataset):
    """Parity: paddle.io.ChainDataset — chain iterable datasets."""

    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Sampler:
    """Parity: paddle.io.Sampler base."""

    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator  # int seed or None
        # persistent generator state: an int seed fixes the STREAM, not
        # every epoch's permutation — successive __iter__ calls must
        # reshuffle (reference semantics: paddle's generator state
        # advances across epochs)
        self._rng = np.random.default_rng(
            generator if isinstance(generator, int) else None)

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = self._rng
        if self.replacement:
            return iter(rng.integers(0, n, self.num_samples).tolist())
        if self.num_samples > n:
            raise ValueError(
                f"RandomSampler: num_samples={self.num_samples} exceeds "
                f"dataset size {n} without replacement")
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    """Parity: paddle.io.SubsetRandomSampler — a random permutation of
    the given index subset each epoch."""

    def __init__(self, indices):
        super().__init__(None)
        self.indices = list(indices)
        self._rng = np.random.default_rng()

    def __iter__(self):
        return iter(self.indices[i] for i in
                    self._rng.permutation(len(self.indices)))

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, np.float64)
        assert self.weights.ndim == 1 and (self.weights >= 0).all()
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.default_rng()
        return iter(
            rng.choice(
                len(self.weights), self.num_samples,
                replace=self.replacement, p=p,
            ).tolist()
        )

    def __len__(self):
        return self.num_samples


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def get_worker_info():
    """Parity: paddle.io.get_worker_info — None in the main process; in a
    process worker, identifies the worker so IterableDatasets can shard
    their stream."""
    return _worker_state.get("worker_info")


class BatchSampler:
    def __init__(self, dataset=None, sampler=None, shuffle: bool = False,
                 batch_size: int = 1, drop_last: bool = False, seed: int = 0):
        self.dataset = dataset
        self.sampler = sampler
        self.shuffle = shuffle
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.seed = seed
        self.epoch = 0

    def __iter__(self):
        if self.sampler is not None:
            indices = list(iter(self.sampler))
        else:
            indices = list(range(len(self.dataset)))
            if self.shuffle:
                rng = np.random.default_rng(self.seed + self.epoch)
                rng.shuffle(indices)
        batch = []
        for i in indices:
            batch.append(i)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.dataset) if self.sampler is None else len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return math.ceil(n / self.batch_size)

    def set_epoch(self, epoch: int):
        self.epoch = epoch


class DistributedBatchSampler(BatchSampler):
    """Parity: paddle.io.DistributedBatchSampler — pads/splits the index
    space across data-parallel ranks deterministically per epoch."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False, seed: int = 0):
        super().__init__(dataset, None, shuffle, batch_size, drop_last, seed)
        if num_replicas is None:
            num_replicas = jax.process_count()
        if rank is None:
            rank = jax.process_index()
        self.nranks = num_replicas
        self.local_rank = rank
        self.num_samples = math.ceil(len(dataset) / num_replicas)
        self.total_size = self.num_samples * num_replicas

    def __iter__(self):
        indices = list(range(len(self.dataset)))
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            rng.shuffle(indices)
        # pad to evenly divisible
        indices += indices[: self.total_size - len(indices)]
        local = indices[self.local_rank::self.nranks]
        batch = []
        for i in local:
            batch.append(i)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return math.ceil(self.num_samples / self.batch_size)


def default_collate_fn(batch):
    """Stack samples into numpy batches (dicts/tuples handled)."""
    elem = batch[0]
    if isinstance(elem, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in elem}
    if isinstance(elem, (tuple, list)):
        return type(elem)(
            default_collate_fn([b[i] for b in batch]) for i in range(len(elem))
        )
    return np.stack([np.asarray(b) for b in batch])


# --- process-worker plumbing (module-level: fork children resolve these
# by reference; also keeps them picklable if a spawn context is ever used) ---
_worker_state = {}


def _proc_worker_init(dataset, collate_fn, id_counter=None, num_workers=1):
    # Workers are pure-numpy sample loaders and must stay that way: fork
    # children inherit the parent's already-initialized jax backend, so
    # touching jax in a worker is undefined (the env vars below only
    # protect a worker whose first jax import happens post-fork).
    import os as _os

    _os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    _worker_state["dataset"] = dataset
    _worker_state["collate"] = collate_fn
    if id_counter is not None:
        # fork-inherited shared counter: atomic handout, no feeder-thread
        # race (an mp.Queue flushed by a background thread can look empty
        # to an early worker and hand out duplicate ids)
        with id_counter.get_lock():
            wid = id_counter.value
            id_counter.value += 1
        _worker_state["worker_info"] = WorkerInfo(
            id=wid, num_workers=num_workers, dataset=dataset
        )


def _proc_load_batch(idxs):
    ds = _worker_state["dataset"]
    return _worker_state["collate"]([ds[i] for i in idxs])


class DataLoader:
    """Parity: paddle.io.DataLoader. num_workers>0 uses a thread pool for
    sample loading by default (numpy-heavy transforms release the GIL);
    ``use_process_workers=True`` switches to real OS processes (fork
    context — workers inherit the dataset and run pure-Python/numpy
    sample loading only, never touching the device runtime), the
    reference's multiprocess DataLoader semantics for Python-bound
    decode pipelines (PIL/augmentation) that a thread pool cannot
    parallelize. Fork (not spawn) so scripts run from stdin/REPL work —
    spawn would re-import an unimportable __main__."""

    def __init__(
        self,
        dataset,
        batch_sampler: Optional[BatchSampler] = None,
        batch_size: int = 1,
        shuffle: bool = False,
        drop_last: bool = False,
        collate_fn: Optional[Callable] = None,
        num_workers: int = 0,
        prefetch_factor: int = 2,
        use_process_workers: bool = False,
        **kw,
    ):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_process_workers = use_process_workers
        if isinstance(dataset, IterableDataset):
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last,
            )

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def _load_batch(self, idxs):
        return self.collate_fn([self.dataset[i] for i in idxs])

    def __iter__(self) -> Iterator:
        if isinstance(self.dataset, IterableDataset):
            yield from self._iter_iterable()
            return
        if self.num_workers <= 0:
            for idxs in self.batch_sampler:
                yield self._load_batch(idxs)
            return
        # prefetch pipeline over a worker pool (threads or processes)
        if self.use_process_workers:
            import multiprocessing as mp
            from concurrent.futures import ProcessPoolExecutor

            ctx = mp.get_context("fork")
            id_counter = ctx.Value("i", 0)
            pool_cm = ProcessPoolExecutor(
                max_workers=self.num_workers,
                mp_context=ctx,
                initializer=_proc_worker_init,
                initargs=(self.dataset, self.collate_fn, id_counter,
                          self.num_workers),
            )
            submit = _proc_load_batch
        else:
            from concurrent.futures import ThreadPoolExecutor

            pool_cm = ThreadPoolExecutor(max_workers=self.num_workers)
            submit = self._load_batch

        with pool_cm as pool:
            pending: "queue.Queue" = queue.Queue()
            it = iter(self.batch_sampler)
            depth = self.num_workers * self.prefetch_factor
            for idxs in itertools.islice(it, depth):
                pending.put(pool.submit(submit, idxs))
            for idxs in it:
                yield pending.get().result()
                pending.put(pool.submit(submit, idxs))
            while not pending.empty():
                yield pending.get().result()

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)

    def __call__(self):
        # legacy paddle spelling: `for batch in loader():` — the
        # fluid-era DataLoader was callable and 2.x kept it working;
        # many tutorials (and migrating scripts) use this form
        return iter(self)


def prefetch_to_device(iterator: Iterable, size: Optional[int] = None,
                       sharding=None) -> Iterator:
    """Double-buffered host→device prefetch (parity: the pinned-memory +
    stream H2D overlap in the reference's DataLoader). ``size``
    defaults to ``PT_FLAGS_io_prefetch_depth`` (2)."""
    if size is None:
        from .. import flags

        size = int(flags.flag("io_prefetch_depth"))
    buf: "queue.Queue" = queue.Queue(maxsize=size)
    sentinel = object()

    def put(x):
        if sharding is not None:
            return jax.tree_util.tree_map(
                lambda a: jax.device_put(a, sharding), x
            )
        return jax.tree_util.tree_map(jax.device_put, x)

    def producer():
        for item in iterator:
            buf.put(put(item))
        buf.put(sentinel)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    while True:
        item = buf.get()
        if item is sentinel:
            return
        yield item
