"""ctypes binding for the native token data loader (csrc/dataloader.cpp).

Parity: the reference's native reader/worker pipeline — this keeps token
batch materialization (mmap reads + shuffle + copy) off the Python
interpreter; Python only pops finished int32 buffers and device_puts.

Builds the .so on first use (g++ is in the image); falls back cleanly —
callers should catch ImportError/OSError and use the pure-python
DataLoader.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator, Optional, Tuple

import numpy as np

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
_SO = os.path.join(_CSRC, "libptdataloader.so")
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_SO):
        subprocess.run(
            ["make", "-C", _CSRC], check=True, capture_output=True
        )
    lib = ctypes.CDLL(_SO)
    lib.ptdl_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int64]
    lib.ptdl_open.restype = ctypes.c_int
    lib.ptdl_num_seqs.argtypes = [ctypes.c_int]
    lib.ptdl_num_seqs.restype = ctypes.c_int64
    lib.ptdl_start_epoch.argtypes = [
        ctypes.c_int, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
        ctypes.c_int, ctypes.c_int,
    ]
    lib.ptdl_start_epoch.restype = ctypes.c_int
    lib.ptdl_next_batch.argtypes = [
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.ptdl_next_batch.restype = ctypes.c_int64
    lib.ptdl_close.argtypes = [ctypes.c_int]
    lib.ptdl_close.restype = ctypes.c_int
    _lib = lib
    return lib


class TokenBinDataset:
    """Fixed-length sequences from a binary token shard (uint16/uint32)."""

    def __init__(self, path: str, seq_len: int, token_bytes: int = 2):
        lib = _load()
        self._lib = lib
        self.seq_len = seq_len
        self.handle = lib.ptdl_open(
            path.encode(), token_bytes, seq_len
        )
        if self.handle < 0:
            raise OSError(
                f"ptdl_open({path!r}) failed with code {self.handle}"
            )
        self.num_seqs = lib.ptdl_num_seqs(self.handle)

    def __len__(self):
        return self.num_seqs

    def batches(
        self,
        batch_size: int,
        seed: int = 0,
        shuffle: bool = True,
        drop_last: bool = True,
        num_threads: int = 2,
        return_indices: bool = False,
    ) -> Iterator[np.ndarray]:
        lib = self._lib
        rc = lib.ptdl_start_epoch(
            self.handle, seed, batch_size, int(drop_last), int(shuffle),
            num_threads,
        )
        if rc != 0:
            raise OSError(f"ptdl_start_epoch failed: {rc}")
        buf = np.empty((batch_size, self.seq_len), np.int32)
        idx = np.empty((batch_size,), np.int64)
        while True:
            n = lib.ptdl_next_batch(
                self.handle,
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            )
            if n <= 0:
                return
            batch = buf[:n].copy()
            if return_indices:
                yield batch, idx[:n].copy()
            else:
                yield batch

    def close(self):
        if self.handle >= 0:
            self._lib.ptdl_close(self.handle)
            self.handle = -1

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


_ckpt_lib = None
_CKPT_SO = os.path.join(_CSRC, "libptckpt.so")


def load_ckpt_writer():
    """ctypes handle for the native parallel checkpoint chunk writer
    (csrc/ckptio.cpp). Builds on first use; raises on failure — callers
    fall back to the pure-python np.save loop. Build failure is cached
    so periodic saves don't re-spawn a doomed make each time."""
    global _ckpt_lib
    if _ckpt_lib is False:
        raise OSError("native checkpoint writer unavailable (cached)")
    if _ckpt_lib is not None:
        return _ckpt_lib
    if not os.path.exists(_CKPT_SO):
        try:
            subprocess.run(["make", "-C", _CSRC], check=True,
                           capture_output=True)
        except Exception:
            _ckpt_lib = False
            raise
    lib = ctypes.CDLL(_CKPT_SO)
    lib.ptck_write_batch.argtypes = [
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.ptck_write_batch.restype = ctypes.c_int
    _ckpt_lib = lib
    return lib
