"""Global flag registry.

Parity: the FLAGS_* system (paddle/utils/flags/ vendored gflags-workalike
+ paddle.set_flags/get_flags): process-level knobs settable via env
(``PT_FLAGS_xxx=``) or at runtime.

TPU-native: most reference flags configure the CUDA allocator/cudnn/NCCL
and are subsumed by XLA; the registry carries the framework-level knobs
that remain meaningful and passes xla_* entries through to XLA_FLAGS at
first-use time.
"""

from __future__ import annotations

import os
from typing import Any, Dict

_REGISTRY: Dict[str, Dict[str, Any]] = {}


def define_flag(name: str, default, help_: str = ""):
    env = os.environ.get(f"PT_FLAGS_{name}")
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes", "on")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    _REGISTRY[name] = {"value": value, "default": default, "help": help_}
    return value


def set_flags(flags: Dict[str, Any]):
    """Parity: paddle.set_flags({"FLAGS_x": v})."""
    for name, value in flags.items():
        key = name.removeprefix("FLAGS_")
        if key not in _REGISTRY:
            raise KeyError(f"unknown flag {name!r}")
        _REGISTRY[key]["value"] = value


def get_flags(names):
    if isinstance(names, str):
        names = [names]
    out = {}
    for name in names:
        key = name.removeprefix("FLAGS_")
        if key not in _REGISTRY:
            raise KeyError(f"unknown flag {name!r}")
        out[name] = _REGISTRY[key]["value"]
    return out


def flag(name: str):
    return _REGISTRY[name]["value"]


def all_flags():
    return {k: v["value"] for k, v in _REGISTRY.items()}


def registry():
    """The CANONICAL flag registry view: name -> {value, default,
    help}. This is the single source the static lint's flags-hygiene
    rules (``paddle_tpu.analysis.lint``, FL001–FL003) and the
    registry-consistency tests check against — every ``PT_FLAGS_*``
    read anywhere in the repo must resolve here (flags defined in
    other modules, e.g. ``nn/layout.py``'s ``conv_layout``, register
    through the same ``define_flag`` and appear too). Returns copies;
    mutate flags through ``set_flags``."""
    return {k: dict(v) for k, v in _REGISTRY.items()}


# ---------------------------------------------------------------------------
# built-in flags (the meaningful survivors of the reference's ~hundreds)
# ---------------------------------------------------------------------------
define_flag("benchmark", False,
            "print per-step wall timing + loss from TrainStep.run "
            "(blocks on the step's outputs each step — a debug/bench "
            "knob, not a production setting)")
define_flag("check_nan_inf", False,
            "debug-check each TrainStep's loss/grad-norm for NaN/Inf "
            "and raise FloatingPointError at the offending step "
            "(forces a per-step host sync; read at TrainStep build "
            "time, where it also forces the grad-norm output on even "
            "with telemetry off)")
define_flag("default_matmul_precision", "",
            "process-wide jax matmul precision override, applied at "
            "import: bfloat16|tensorfloat32|float32|highest; empty = "
            "jax's default (bf16 on the MXU)")
define_flag("log_memory_stats", False,
            "record device bytes_in_use/peak_bytes_in_use through the "
            "telemetry registry on sampled steps")
define_flag("telemetry", True,
            "always-on runtime telemetry (observability.MetricsRegistry); "
            "off = every instrumented path is a no-op")
define_flag("telemetry_sample_every", 10,
            "fetch loss/grad-norm/memory host-side every N train steps "
            "(non-sampled steps never force a device sync)")
define_flag("telemetry_flight_window", 64,
            "flight-recorder ring buffer size (last K step records)")
define_flag("telemetry_dump_dir", "flight_records",
            "directory for flight-recorder JSON dumps")
define_flag("telemetry_grad_spike_factor", 10.0,
            "anomaly watchdog trips when grad norm exceeds this factor "
            "times the running median")
define_flag("trace_sample", 1.0,
            "serving lifecycle tracer sample rate in (0, 1]: the "
            "fraction of requests and engine steps recorded "
            "(deterministic — every round(1/rate)-th request id / step "
            "sequence number, so a sampled request's events are "
            "complete, never a torn subset). 0 disables the tracer "
            "entirely; PT_FLAGS_telemetry=off disables it regardless")
define_flag("trace_buffer", 8192,
            "ring capacity (events) of each serving tracer — old events "
            "fall off; bounds host memory no matter how long the engine "
            "runs")
define_flag("rng_use_global_seed", True,
            "derive the eager rng stream (core.random.default_key) "
            "from the global paddle_tpu.seed; off = draw the stream's "
            "base from OS entropy once per thread (non-reproducible "
            "by request)")
define_flag("fused_group_norm", True,
            "dispatch NHWC GroupNorm to the fused Pallas kernel")
define_flag("fused_decode", "auto",
            "fused single-pass decode attention (in-kernel RoPE + KV "
            "append + length-pruned streaming): auto = compiled kernel "
            "on TPU when shapes tile, lax reference elsewhere; "
            "on = force (Pallas interpret mode off-TPU); off = unfused")
define_flag("prefix_cache", True,
            "serving prefix KV reuse: admission looks up the longest "
            "cached block-aligned prompt prefix and prefills only the "
            "suffix (paged mode shares pages copy-on-write; contiguous "
            "mode copies cached token blocks into the slot). off = "
            "every request recomputes its full prompt")
define_flag("prefill_chunk", 256,
            "serving prefill chunk length: ONE compiled fixed-size-chunk "
            "program (clamped to [2, max_len] — a 1-token chunk would "
            "fall into the decode step's clamped append) drives prefill "
            "in a host loop — compute ∝ suffix rounded up to the chunk, "
            "not the seq bucket, and compile count drops from "
            "len(seq_buckets) to 1. 0 = legacy per-bucket prefill (the "
            "parity oracle)")
define_flag("spec_decode", "off",
            "speculative decoding in the serving engine: draft K "
            "candidate tokens per slot per step (host-side n-gram "
            "prompt-lookup — no draft model weights) and score them in "
            "ONE fixed [slots, K+1] target-model pass with in-jit "
            "greedy acceptance, amortizing the per-step weight stream "
            "over accepted+1 tokens. ngram = draft whenever the slot's "
            "history matches; auto = ngram with a per-request throttle "
            "that stops drafting traffic that never accepts; off = "
            "today's one-token-per-pass decode (the parity oracle — "
            "greedy outputs are identical in every mode)")
define_flag("fault_inject", "",
            "serving fault injector (chaos testing): comma-separated "
            "site:rate entries over the engine's dispatch seams — "
            "step (dispatch exception), nan (NaN-logits storm), "
            "latency (stall before dispatch), pool (simulated KV-pool "
            "exhaustion at admission) — plus seed:<int> and "
            "latency_ms:<float>, e.g. 'step:0.1,nan:0.05,seed:7'. "
            "Each site draws from its own seeded RNG stream, so chaos "
            "runs are deterministic and CPU-runnable. Empty = off "
            "(zero overhead)")
define_flag("serve_recovery", "auto",
            "step-level crash recovery in the serving engine: catch a "
            "failed decode/verify/prefill dispatch, quarantine the "
            "step and re-queue its in-flight requests for "
            "deterministic replay (prompt+history re-prefilled "
            "through the existing chunked-prefill program; greedy "
            "outputs stay bit-identical), with bounded per-request "
            "retries (EngineConfig.max_retries). auto = recover "
            "injected faults and XLA runtime errors, propagate host "
            "logic errors; all = recover any Exception; off = every "
            "fault propagates")
define_flag("degradation", True,
            "graceful-degradation ladder in the serving engine: "
            "sustained admission saturation sheds batch-class "
            "admissions then throttles admission; repeated step "
            "faults additionally disable speculative decoding and "
            "prefix-cache adoption (min_service). Surfaced through "
            "backpressure()/healthz/the tracer; never changes greedy "
            "outputs. off = the controller is not constructed")
define_flag("kv_cache_dtype", "auto",
            "serving KV-cache dtype when EngineConfig.cache_dtype is "
            "'auto': auto = bfloat16 on TPU (halves decode KV traffic), "
            "float32 elsewhere; or explicit "
            "bfloat16|float16|float32|int8. int8 stores per-row f32 "
            "scales alongside the pools (per page-row paged, per block "
            "row contiguous), quantizes on append and dequantizes "
            "inside the fused decode kernels — KV stream bytes halve "
            "again vs bf16; greedy outputs may differ from the fp "
            "cache (the serve7b 'quant' bench scenario MEASURES that "
            "delta, outputs_match + first-divergence index)")
define_flag("serve_weight_dtype", "bf16",
            "serving weight stream when EngineConfig.weight_dtype is "
            "'auto': bf16 = serve the model's own weights; int8/int4 = "
            "group-wise weight-only quantization at engine init "
            "(quantize_model_weight_only), weights + scales ride every "
            "compiled serving program as jit arguments and dequantize "
            "in-kernel (weight_only_matmul_pallas on TPU, the XLA "
            "dequant reference elsewhere) — weight HBM traffic drops "
            "2x/4x, the decode roofline's other half. Single-chip "
            "serving only (no mesh); quality delta is measured, not "
            "asserted away, by the serve7b 'quant' scenario")
define_flag("sanitize", False,
            "serving-engine runtime invariant sanitizer "
            "(analysis/sanitizer.py): once per scheduler tick, check "
            "page/refcount conservation, slot-heap + block-table + "
            "int8-scale-pool agreement and seq_len bounds against the "
            "host token ledger, plus thread-ownership of scrape-"
            "thread reads (only the registered copy-on-read snapshot "
            "methods may be called from a foreign thread). Violations "
            "raise SanitizerError naming the invariant and site. "
            "off = every hook is a single identity check (the "
            "telemetry=off pattern); `pytest -m chaos` runs with it "
            "on. Host bookkeeping only — zero compiled programs, "
            "zero device syncs")
define_flag("profile_programs", False,
            "serving per-program device-time profiler "
            "(observability/profiling.py): cadence-sampled "
            "block-until-ready timing around every compiled serving "
            "dispatch (prefill_chunk/prefill_bucket/decode_step/"
            "decode_chunk/spec_verify/page_copy). Sampled dispatches "
            "record MEASURED device ms into "
            "pt_serve_program_ms{engine,program} plus a host-schedule/"
            "dispatch/device decomposition on the tracer's step "
            "events; unsampled dispatches stay fully async (no host "
            "sync — the PR-2 cadence discipline). off = the engine "
            "holds no profiler, one identity check per seam, zero new "
            "compiled programs")
define_flag("profile_sample_every", 16,
            "profile_programs sample cadence: measure every Nth "
            "dispatch of each program (per-program counters, "
            "deterministic). 1 = measure every dispatch — full "
            "attribution at the cost of one device sync per dispatch; "
            "note a program's FIRST dispatch (its compile) is only "
            "sampled at cadence 1")
define_flag("recompile_watchdog", True,
            "runtime recompile watchdog: after "
            "recompile_warmup_ticks scheduler ticks (or an explicit "
            "engine.seal_programs()) the engine's expected "
            "compiled-program set is SEALED; any later TRACE_COUNTS "
            "growth during one of this engine's own ticks counts "
            "pt_serve_recompiles_total{engine,program} and (telemetry "
            "on) dumps a FlightRecorder artifact carrying the "
            "offending specialization's arg shapes — the production "
            "complement to ptlint TS003 and the test-only "
            "compile-count guards. A program whose FIRST legitimate "
            "use lands after the seal (e.g. page_copy on the first "
            "copy-on-write) counts once — size the warmup, or seal "
            "explicitly after real warmup traffic. One artifact per "
            "program per engine; counters keep counting. Never "
            "raises; off = no watchdog, one identity check per tick")
define_flag("audit_on_seal", False,
            "run the ptaudit jaxpr contract audit "
            "(analysis/program_audit.py: donation/aliasing, dtype "
            "discipline, transfer bans, dead operands) over the "
            "engine's OWN compiled programs at its real shapes when "
            "seal_programs() seals the set — a trace-only self-audit "
            "(no compile, no dispatch, TRACE_COUNTS restored so the "
            "watchdog and compile-count guards never see it); the "
            "verdict surfaces in metrics_snapshot()['audit']. Off = "
            "one identity check at seal. Size budgets (SZ) stay with "
            "the CLI's canonical tiny arms")
define_flag("timeseries", False,
            "serving flight-data recorder "
            "(observability/timeseries.py): a bounded ring of "
            "fixed-cadence windowed samples over the engine's/"
            "router's metrics — counter deltas become per-window "
            "rates, gauges are point-sampled, histogram window-"
            "percentiles ride along (telemetry on). Tick-driven and "
            "wall-clock-free in every decision, scrape-thread-safe "
            "copy-on-read; read via engine.timeline_snapshot(), the "
            "/timeline endpoint and `dump --timeline`. off = no "
            "store is constructed (one identity check per tick, zero "
            "new compiled programs, outputs bit-identical)")
define_flag("timeseries_cadence", 16,
            "scheduler ticks per time-series window: every Nth tick "
            "closes a window and appends one sample (counter deltas "
            "over exactly N ticks — deterministic)")
define_flag("timeseries_retention", 256,
            "time-series ring capacity (windows): old samples fall "
            "off, bounding host memory no matter how long the engine "
            "runs; at the default cadence x retention this is the "
            "last ~4k scheduler ticks of history")
define_flag("alerts", True,
            "rule-based detectors over the serving time-series "
            "(observability/alerts.py): multi-window SLO burn-rate, "
            "queue-depth growth, prefix-hit / spec-acceptance "
            "collapse, post-seal recompiles, HBM residency — each "
            "with hysteresis (no flapping), firing structured "
            "`alert` tracer events + a FlightRecorder artifact "
            "carrying the triggering window, surfaced in "
            "metrics_snapshot()['alerts'] and the fleet snapshot. "
            "Evaluated only when PT_FLAGS_timeseries is on (the "
            "rules read the series); off = no detectors constructed")
define_flag("cost_attribution", True,
            "per-request device-cost attribution: each step's "
            "measured program-ms (profiler-sampled; sync-wall "
            "estimate on unsampled steps) is split across the "
            "requests the step advanced, proportional to tokens "
            "advanced, accumulated on the request and recorded at "
            "finish into pt_serve_request_device_ms{engine,slo} and "
            "the request ledger (cost survives failover/drain "
            "handoffs); read via engine.cost_snapshot(). Pure host "
            "arithmetic — zero device syncs, zero new compiled "
            "programs. off = requests carry device_ms 0 (one "
            "identity check per seam, outputs bit-identical)")
define_flag("slo_degradation", False,
            "let the degradation ladder consume the SLO burn-rate "
            "alert (read-only AlertManager.is_active hook): an "
            "active slo_burn_rate counts as saturation pressure, so "
            "sustained burn climbs the CAPACITY rungs (shed batch-"
            "class admissions, throttle) even before the queue "
            "backs up — never the fault jump. Requires timeseries + "
            "alerts on to have any effect; off (default) leaves the "
            "ladder's inputs untouched (outputs pinned identical)")
define_flag("tenant_prefix_namespace", True,
            "multi-tenant prefix-cache isolation: tenant-tagged "
            "requests hash their prompt blocks under a per-tenant "
            "namespace seed, so tenants can neither probe for nor "
            "borrow each other's cached KV, and pool-pressure "
            "eviction spends the requesting tenant's own cold "
            "entries first. Untagged requests (tenant=None) always "
            "share the default chain — single-tenant traffic is "
            "bit-identical either way. off = all tenants share one "
            "namespace (maximum reuse, zero isolation)")
define_flag("sched_policy", "fifo",
            "serving front door's default admission scheduler when "
            "none is passed to start_api_server: fifo = the engine's "
            "native submission-order admission; slo_fair = "
            "serving_api.SLOFairScheduler (per-tenant weighted fair "
            "share + TTFT-deadline urgency decide admission order, "
            "chunk split and preemption). An explicit scheduler= "
            "argument always wins")
define_flag("api_max_tenants", 256,
            "serving front door: maximum DISTINCT tenant ids accepted "
            "over the server's lifetime — tenant strings are "
            "client-controlled and each unique value mints permanent "
            "per-tenant metric series, accounting buckets and "
            "fair-share ledger entries, so unbounded cardinality is a "
            "memory/scrape DoS; past the cap, requests carrying a NEW "
            "tenant are rejected with HTTP 429 (known tenants and "
            "untagged requests always pass; 0 rejects every "
            "tenant-tagged API request)")
define_flag("sched_preempt", True,
            "allow the SLO-fair scheduler to PREEMPT an active "
            "batch-class slot (release slot/pages, re-queue with "
            "history for deterministic replay through the existing "
            "prefill program — zero new compiled programs) when an "
            "interactive request is about to miss its TTFT target "
            "and no slot is free; bounded per request. off = "
            "admission reordering and quotas only")
define_flag("recompile_warmup_ticks", 64,
            "scheduler ticks before the recompile watchdog auto-seals "
            "the program set (warmup compiles are expected; "
            "engine.seal_programs() seals immediately, e.g. right "
            "after a bench warmup)")
define_flag("router_breaker_window", 16,
            "multi-engine router: sliding window (fleet ticks) the "
            "per-replica circuit breaker counts faults over — "
            "router_breaker_trip faults inside it open the breaker "
            "(the replica stops receiving traffic and its in-flight "
            "requests fail over to survivors)")
define_flag("router_breaker_trip", 3,
            "multi-engine router: replica faults (failed ticks, hung "
            "health probes, flaky probe verdicts) within the breaker "
            "window that OPEN a replica's circuit breaker; a whole-"
            "replica crash opens it immediately regardless")
define_flag("router_breaker_cooldown", 8,
            "multi-engine router: base open-state duration (fleet "
            "ticks) before an open breaker admits a half-open canary "
            "probe; successive opens multiply it by the "
            "router_retry_schedule entries plus a seeded jitter "
            "(deterministic per router seed + replica)")
define_flag("router_retry_schedule", "1,2,4",
            "multi-engine router: comma-separated cooldown "
            "multipliers for successive breaker opens (the last entry "
            "repeats) — with cooldown 8 the default backs off "
            "8/16/32/32/... ticks. Deterministic: the only randomness "
            "is a per-replica jitter drawn from a stream seeded on "
            "(router seed, replica index)")
define_flag("flash_attention_block_q", 1024,
            "Pallas flash-attention q block length (rows of q each "
            "kernel grid step keeps in VMEM; clamped to the padded "
            "sequence). Default matches the kernel's "
            "DEFAULT_Q_BLOCK, so the flag is a pure override knob")
define_flag("flash_attention_block_k", 1024,
            "Pallas flash-attention k/v block length (the online-"
            "softmax streaming granularity; clamped to the padded "
            "sequence). Default matches DEFAULT_K_BLOCK")
define_flag("moe_capacity_factor", 1.25,
            "default MoE expert capacity factor when a layer doesn't "
            "pass one explicitly (capacity = factor * tokens * top_k "
            "/ num_experts)")
define_flag("io_prefetch_depth", 2,
            "host→device prefetch buffers (io.prefetch_to_device "
            "default queue depth)")
