"""``python -m paddle_tpu.analysis.check`` — ptlint + ptaudit in one
gate with one exit code.

The CI/tooling front door for the whole static-analysis layer: the
AST lint over the source tree AND the jaxpr contract audit over the
compiled serving program set, each against its committed baseline.

Usage::

    python -m paddle_tpu.analysis.check                # full repo
    python -m paddle_tpu.analysis.check --json
    python -m paddle_tpu.analysis.check --arms paged-bf16

Exit status: 0 when BOTH halves are clean, 1 when either reports a
violation, 2 on usage errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from . import lint

_LINT_PATHS = ("paddle_tpu", "tests", "benchmarks")


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ptcheck",
        description="run ptlint (AST) + ptaudit (jaxpr contracts) "
                    "together: one gate, one exit code")
    ap.add_argument("--root", default=None,
                    help="repo root (default: nearest pyproject.toml)")
    ap.add_argument("--arms", default=None,
                    help="comma-separated ptaudit arm subset")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable combined output")
    args = ap.parse_args(argv)

    root = args.root or lint.find_root(
        os.path.dirname(os.path.abspath(__file__)))
    paths = [os.path.join(root, p) for p in _LINT_PATHS
             if os.path.exists(os.path.join(root, p))]
    if not paths:
        print(f"ptcheck: no scan paths under {root}", file=sys.stderr)
        return 2

    # ---- ptlint half ----
    result = lint.scan(paths, root)
    try:
        baseline = lint.load_baseline(
            os.path.join(root, lint.BASELINE_NAME))
    except ValueError as e:
        print(f"ptcheck: {e}", file=sys.stderr)
        return 2
    lint_new, _accepted = lint.apply_baseline(
        result.violations, baseline)

    # ---- ptaudit half (jax-heavy import deferred past the lint) ----
    from . import program_audit as PA

    # the audit half traces the IMPORTED package's programs against
    # that tree's baseline — a --root pointing at a different
    # checkout would silently gate one tree's lint with another
    # tree's audit, so refuse the mix outright
    pkg_root = lint.find_root(
        os.path.dirname(os.path.abspath(PA.__file__)))
    if os.path.realpath(root) != os.path.realpath(pkg_root):
        print(f"ptcheck: --root {root} is not the imported "
              f"paddle_tpu's repo ({pkg_root}) — the audit half can "
              "only trace the imported package; run ptcheck from "
              "that checkout instead", file=sys.stderr)
        return 2

    arm_names = [a.strip() for a in args.arms.split(",")] \
        if args.arms else None
    try:
        audit = PA.audit_repo(arms=arm_names)
    except (PA.AuditError, ValueError) as e:
        print(f"ptcheck: {e}", file=sys.stderr)
        return 2
    audit_viol = audit["violations"]

    if args.as_json:
        print(json.dumps({
            "lint": {"files": result.files,
                     "violations": [v.__dict__ for v in lint_new]},
            "audit": {"programs": sorted(audit["entries"]),
                      "violations": [v.__dict__ for v in audit_viol]},
        }, indent=2))
        return 1 if (lint_new or audit_viol) else 0

    for v in lint_new:
        print(f"{v.file}:{v.line}: {v.rule} {v.message}")
    for x in audit_viol:
        print(f"{x.arm}::{x.program}: {x.rule} {x.message}")
    print(f"ptcheck: lint {result.files} file(s) "
          f"{len(lint_new)} violation(s); audit "
          f"{len(audit['entries'])} program(s) "
          f"{len(audit_viol)} violation(s)")
    return 1 if (lint_new or audit_viol) else 0


if __name__ == "__main__":
    sys.exit(main())
