"""ptlint rule implementations (stdlib-only: ``ast`` + regex).

Four rule families, each mechanizing a class of review finding this
codebase has already paid for at runtime (see ISSUE/CHANGES history —
the slo_snapshot scrape race, the `_pool_blocked` visibility gap, the
coordinated-omission TTFT fix were all findable by these rules):

* **TS — trace safety.** Host syncs and Python control flow on traced
  values inside directly-jitted program bodies, jit wrappers built
  inside loops (each ``jax.jit`` object owns its own compile cache — a
  fresh wrapper per iteration recompiles every time), and — in modules
  that carry a ``TRACE_COUNTS`` compile-accounting counter — jitted
  program bodies that fail to register a name in it (a blind spot for
  the tests' compile-count guards).

* **DT — determinism.** The crash-recovery replay and spec-verify
  paths promise bit-identical outputs; unseeded randomness and
  wall-clock reads in ``paddle_tpu/inference`` / ``paddle_tpu/kernels``
  are how that promise quietly breaks. ``time.perf_counter`` (latency
  measurement, never a decision input) stays allowed; ``time.time``
  does not — artifact timestamps belong to the flight recorder.

* **FL — flags hygiene.** Every ``flag("x")`` / ``get_flags`` /
  ``set_flags`` literal must resolve against the canonical registry
  (``flags.py`` plus any ``define_flag`` call site, e.g.
  ``nn/layout.py``); every defined flag must be read somewhere outside
  ``tests/`` (else it is dead weight) and documented in README's flags
  tables.

* **CC — concurrency (copy-on-read).** Engine host structures are
  scheduler-owned. Reader methods the metrics/scrape thread may call
  (``*_snapshot`` / ``snapshot`` / ``backpressure`` / ``_tel_state``)
  must iterate *copies* — ``list(x.items())`` is the blessed marker —
  and must not mutate scheduler state, directly or one self-call
  level down. The runtime side of the same contract lives in
  ``analysis/sanitizer.py`` (thread-ownership checker).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------------------
# shared plumbing
# ---------------------------------------------------------------------------


@dataclass
class Violation:
    file: str  # repo-relative posix path
    line: int
    rule: str
    message: str

    def key(self) -> str:
        return f"{self.file}::{self.rule}"


class Project:
    """Cross-file scan state: the flag registry view and read/write
    sites accumulate here module by module; project-level rules
    (FL001-FL003) run once after every module has been scanned."""

    def __init__(self, root: str):
        self.root = root
        # flag name -> (file, line) of its define_flag site
        self.flag_defs: Dict[str, Tuple[str, int]] = {}
        # flag name -> [(file, line)] of flag()/get_flags reads
        self.flag_reads: Dict[str, List[Tuple[str, int]]] = {}
        # flag name -> [(file, line)] of set_flags writes
        self.flag_writes: Dict[str, List[Tuple[str, int]]] = {}
        self.saw_registry_module = False
        # OBS001: TRACE_COUNTS program name -> (file, line) of its
        # first compile-counter bump, and the PROGRAM_LABELS literal
        # keys from observability/profiling.py
        self.trace_programs: Dict[str, Tuple[str, int]] = {}
        self.program_labels: Set[str] = set()
        self.saw_profiling_module = False
        # OBS002: AlertRule implementations (class-level
        # ``name = "..."``) and the canonical ALERT_RULES literal keys
        # from observability/alerts.py
        self.alert_impls: Dict[str, Tuple[str, int]] = {}
        self.alert_rules: Set[str] = set()
        self.saw_alerts_module = False
        # PA001: the PROGRAM_CONTRACTS literal keys from
        # analysis/program_audit.py (trace_programs above is shared
        # with OBS001 — verdicts run after every module is scanned,
        # so rule order in ALL_RULES doesn't matter)
        self.program_contracts: Set[str] = set()
        self.saw_audit_module = False

    def readme_text(self) -> str:
        path = os.path.join(self.root, "README.md")
        try:
            with open(path, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return ""


def _iter_with_parents(tree: ast.AST):
    """Yield (node, parents tuple) in document order."""
    stack = [(tree, ())]
    while stack:
        node, parents = stack.pop()
        yield node, parents
        for child in reversed(list(ast.iter_child_nodes(node))):
            stack.append((child, parents + (node,)))


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _Imports:
    """Module-level alias map for jax / jax.jit / functools.partial."""

    def __init__(self, tree: ast.Module):
        self.jax: Set[str] = set()
        self.jit: Set[str] = set()
        self.partial: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax":
                        self.jax.add(a.asname or "jax")
                    if a.name == "functools":
                        pass
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "jit":
                            self.jit.add(a.asname or "jit")
                if node.module == "functools":
                    for a in node.names:
                        if a.name == "partial":
                            self.partial.add(a.asname or "partial")

    def is_jax_jit(self, func: ast.expr) -> bool:
        if isinstance(func, ast.Name):
            return func.id in self.jit
        if isinstance(func, ast.Attribute) and func.attr == "jit":
            v = func.value
            return isinstance(v, ast.Name) and v.id in (self.jax | {"jax"})
        return False


def _jit_static_names(call: Optional[ast.Call],
                      fd: ast.FunctionDef) -> Optional[Set[str]]:
    """Param names a jit spec marks static. None = spec unparseable
    (the caller then skips control-flow checks to avoid noise)."""
    params = [a.arg for a in fd.args.posonlyargs + fd.args.args]
    static: Set[str] = set()
    if call is None:
        return static
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = []
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.append(e.value)
                else:
                    return None
            for i in nums:
                if 0 <= i < len(params):
                    static.add(params[i])
        elif kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                s = _const_str(e)
                if s is None:
                    return None
                static.add(s)
    return static


def _collect_jitted(tree: ast.Module, imports: _Imports):
    """Directly-jitted function bodies: ``jax.jit(fn, ...)`` over a
    local ``def fn``, and ``@jax.jit`` / ``@partial(jax.jit, ...)``
    decorated defs. Returns [(funcdef, static_names_or_None,
    jit_call_line)]."""
    out = []
    seen: Set[int] = set()
    # decorator forms
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            call = None
            if imports.is_jax_jit(dec):
                pass
            elif isinstance(dec, ast.Call) and imports.is_jax_jit(dec.func):
                call = dec
            elif (isinstance(dec, ast.Call)
                  and isinstance(dec.func, ast.Name)
                  and dec.func.id in imports.partial
                  and dec.args and imports.is_jax_jit(dec.args[0])):
                call = dec
            else:
                continue
            if id(node) not in seen:
                seen.add(id(node))
                out.append((node, _jit_static_names(call, node),
                            node.lineno))
    # jax.jit(fn, ...) over a local def: resolve fn through enclosing
    # scopes, innermost first
    for node, parents in _iter_with_parents(tree):
        if not (isinstance(node, ast.Call)
                and imports.is_jax_jit(node.func) and node.args):
            continue
        target = node.args[0]
        if not isinstance(target, ast.Name):
            continue
        scopes = [p for p in parents
                  if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Module))]
        fd = None
        for scope in reversed(scopes):
            for child in ast.walk(scope):
                if isinstance(child, ast.FunctionDef) \
                        and child.name == target.id:
                    fd = child
                    break
            if fd is not None:
                break
        if fd is not None and id(fd) not in seen:
            seen.add(id(fd))
            out.append((fd, _jit_static_names(node, fd), node.lineno))
    return out


# ---------------------------------------------------------------------------
# rule base
# ---------------------------------------------------------------------------
class Rule:
    id = "XX000"
    #: one-line description printed by ``lint --rules``
    doc = ""

    def applies(self, relpath: str) -> bool:  # noqa: ARG002
        return True

    def check_module(self, project: Project, tree: ast.Module, src: str,
                     relpath: str) -> List[Violation]:  # noqa: ARG002
        return []

    def check_project(self, project: Project) -> List[Violation]:  # noqa: ARG002
        return []


def _in(relpath: str, *prefixes: str) -> bool:
    return any(relpath == p or relpath.startswith(p + "/")
               for p in prefixes)


# ---------------------------------------------------------------------------
# TS — trace safety
# ---------------------------------------------------------------------------
class TS001HostSyncInJit(Rule):
    id = "TS001"
    doc = ("host sync / Python control flow on a traced value inside a "
           "directly-jitted program body")

    _NP = {"np", "numpy"}
    _CASTS = {"float", "int", "bool"}

    def check_module(self, project, tree, src, relpath):
        del project, src, relpath
        imports = _Imports(tree)
        out = []
        for fd, static, _line in _collect_jitted(tree, imports):
            params = {a.arg for a in fd.args.posonlyargs + fd.args.args
                      + fd.args.kwonlyargs}
            traced = params - (static or set())
            out.extend(self._scan(fd, traced,
                                  control_flow=static is not None))
        return out

    def _scan(self, fd: ast.FunctionDef, traced: Set[str],
              control_flow: bool) -> List[Violation]:
        out: List[Violation] = []

        def visit(node, names: Set[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    shadow = {a.arg for a in child.args.posonlyargs
                              + child.args.args + child.args.kwonlyargs}
                    visit(child, names - shadow)
                    continue
                if isinstance(child, ast.Call):
                    f = child.func
                    if isinstance(f, ast.Attribute) and f.attr == "item" \
                            and _names_in(f.value) & names:
                        out.append(self._v(
                            child, "`.item()` on a traced value "
                            "forces a host sync at trace time"))
                    elif (isinstance(f, ast.Name)
                          and f.id in self._CASTS and child.args
                          and _names_in(child.args[0]) & names):
                        out.append(self._v(
                            child, f"`{f.id}()` cast on a traced value "
                            "forces a host sync (keep it in jnp, or "
                            "mark the argument static)"))
                    elif (isinstance(f, ast.Attribute)
                          and f.attr in ("asarray", "array")
                          and isinstance(f.value, ast.Name)
                          and f.value.id in self._NP and child.args
                          and _names_in(child.args[0]) & names):
                        out.append(self._v(
                            child, f"`{f.value.id}.{f.attr}()` on a "
                            "traced value materializes it on the host"))
                elif (control_flow
                      and isinstance(child, (ast.If, ast.While))
                      and _names_in(child.test) & names):
                    kind = "if" if isinstance(child, ast.If) else "while"
                    out.append(self._v(
                        child, f"Python `{kind}` on traced argument(s) "
                        f"{sorted(_names_in(child.test) & names)} — use "
                        "jnp.where/lax.cond, or mark the arg static"))
                visit(child, names)

        for stmt in fd.body:
            visit(stmt, traced)
            # top-level statements themselves (visit only descends)
            if control_flow and isinstance(stmt, (ast.If, ast.While)) \
                    and _names_in(stmt.test) & traced:
                out.append(self._v(
                    stmt, "Python control flow on traced argument(s) "
                    f"{sorted(_names_in(stmt.test) & traced)}"))
        return out

    def _v(self, node, msg):
        return Violation("", node.lineno, self.id, msg)


class TS002TraceCountRegistration(Rule):
    id = "TS002"
    doc = ("in modules carrying a TRACE_COUNTS compile counter, every "
           "directly-jitted program body must register a name in it")

    def check_module(self, project, tree, src, relpath):
        del project, src, relpath
        has_counter = any(
            isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "TRACE_COUNTS"
                for t in n.targets)
            or (isinstance(n, ast.AnnAssign)
                and isinstance(n.target, ast.Name)
                and n.target.id == "TRACE_COUNTS")
            for n in tree.body)
        if not has_counter:
            return []
        imports = _Imports(tree)
        out = []
        for fd, _static, line in _collect_jitted(tree, imports):
            registers = any(
                isinstance(n, ast.AugAssign)
                and isinstance(n.target, ast.Subscript)
                and isinstance(n.target.value, ast.Name)
                and n.target.value.id == "TRACE_COUNTS"
                for n in ast.walk(fd))
            if not registers:
                out.append(Violation(
                    "", fd.lineno, self.id,
                    f"jitted program `{fd.name}` (jit at line {line}) "
                    "does not bump a TRACE_COUNTS name — the compile-"
                    "count guard cannot see its specializations"))
        return out


class TS003JitInLoop(Rule):
    id = "TS003"
    doc = ("jax.jit wrapper constructed inside a loop — every fresh "
           "wrapper owns a fresh compile cache (recompile hazard). "
           "Product code only: bench/test sweeps recompile by design")

    def applies(self, relpath):
        return _in(relpath, "paddle_tpu")

    def check_module(self, project, tree, src, relpath):
        del project, src, relpath
        imports = _Imports(tree)
        out = []
        for node, parents in _iter_with_parents(tree):
            if isinstance(node, ast.Call) and imports.is_jax_jit(node.func):
                if any(isinstance(p, (ast.For, ast.While))
                       for p in parents):
                    out.append(Violation(
                        "", node.lineno, self.id,
                        "jax.jit(...) inside a loop builds a new "
                        "wrapper (and compile cache) per iteration — "
                        "hoist it out and reuse one wrapper"))
        return out


# ---------------------------------------------------------------------------
# DT — determinism (replay / spec-verify paths)
# ---------------------------------------------------------------------------
_DT_SCOPE = ("paddle_tpu/inference", "paddle_tpu/kernels")


class DT001StdlibRandom(Rule):
    id = "DT001"
    doc = ("stdlib `random` in the serving/kernel paths — replay "
           "promises bit-identical outputs; use a seeded "
           "np.random.default_rng stream")

    def applies(self, relpath):
        return _in(relpath, *_DT_SCOPE)

    def check_module(self, project, tree, src, relpath):
        del project, src, relpath
        out = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "random" or a.name.startswith("random."):
                        out.append(Violation(
                            "", node.lineno, self.id,
                            "stdlib `random` is process-global state — "
                            "deterministic replay needs a seeded "
                            "per-site Generator"))
            elif isinstance(node, ast.ImportFrom) and \
                    node.module == "random":
                out.append(Violation(
                    "", node.lineno, self.id,
                    "stdlib `random` import in a deterministic path"))
        return out


class DT002GlobalNumpyRandom(Rule):
    id = "DT002"
    doc = ("global-state numpy randomness in the serving/kernel paths "
           "(np.random.<fn>) — use np.random.default_rng(seed)")

    _BAD = {"seed", "rand", "randn", "random", "randint", "choice",
            "shuffle", "permutation", "random_sample", "standard_normal",
            "uniform", "normal", "get_state", "set_state"}

    def applies(self, relpath):
        return _in(relpath, *_DT_SCOPE)

    def check_module(self, project, tree, src, relpath):
        del project, src, relpath
        out = []
        for node in ast.walk(tree):
            if (isinstance(node, ast.Attribute)
                    and node.attr in self._BAD
                    and isinstance(node.value, ast.Attribute)
                    and node.value.attr == "random"
                    and isinstance(node.value.value, ast.Name)
                    and node.value.value.id in ("np", "numpy")):
                out.append(Violation(
                    "", node.lineno, self.id,
                    f"np.random.{node.attr} draws from process-global "
                    "RNG state — replay determinism needs a seeded "
                    "default_rng stream"))
        return out


class DT003WallClock(Rule):
    id = "DT003"
    doc = ("time.time() in the serving engine — scheduling/replay code "
           "uses perf_counter; wall-clock stamps belong to the "
           "recorder's dump path")

    def applies(self, relpath):
        return _in(relpath, "paddle_tpu/inference")

    def check_module(self, project, tree, src, relpath):
        del project, src, relpath
        out = []
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "time"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "time"):
                out.append(Violation(
                    "", node.lineno, self.id,
                    "time.time() is wall clock (NTP steps, DST): "
                    "durations/deadlines must use time.perf_counter; "
                    "artifact timestamps are the FlightRecorder's job"))
        return out


# ---------------------------------------------------------------------------
# FL — flags hygiene
# ---------------------------------------------------------------------------
class FlagsHygiene(Rule):
    """Collector + three project-level verdicts (FL001/FL002/FL003).

    The canonical registry is ``paddle_tpu/flags.py`` (the satellite
    contract: ``flags.registry()`` exposes it at runtime) plus any
    other ``define_flag`` call site (e.g. ``nn/layout.py``) — both are
    gathered by the same AST scan, so the lint needs no imports."""

    id = "FL001"
    doc = ("flag reads must resolve in the registry; defined flags "
           "must be read outside tests (FL002) and documented in "
           "README's flags tables (FL003)")

    @staticmethod
    def _in_raises(parents) -> bool:
        """True inside a ``with pytest.raises(...)`` block — a flag
        name that is *supposed* to be unknown (negative test) is not a
        hygiene finding."""
        for p in parents:
            if isinstance(p, ast.With):
                for item in p.items:
                    c = item.context_expr
                    if isinstance(c, ast.Call):
                        f = c.func
                        name = f.attr if isinstance(f, ast.Attribute) \
                            else getattr(f, "id", "")
                        if name == "raises":
                            return True
        return False

    def check_module(self, project, tree, src, relpath):
        del src
        if relpath == "paddle_tpu/flags.py":
            project.saw_registry_module = True
        for node, parents in _iter_with_parents(tree):
            if not isinstance(node, ast.Call):
                continue
            if self._in_raises(parents):
                continue
            f = node.func
            fname = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            site = (relpath, node.lineno)
            if fname == "define_flag" and node.args:
                name = _const_str(node.args[0])
                if name is not None and name not in project.flag_defs:
                    project.flag_defs[name] = site
            elif fname == "flag" and node.args:
                name = _const_str(node.args[0])
                if name is not None:
                    project.flag_reads.setdefault(name, []).append(site)
            elif fname == "get_flags" and node.args:
                arg = node.args[0]
                elts = (arg.elts if isinstance(arg, (ast.List, ast.Tuple))
                        else [arg])
                for e in elts:
                    s = _const_str(e)
                    if s is not None:
                        key = s.removeprefix("FLAGS_")
                        project.flag_reads.setdefault(key, []) \
                            .append(site)
            elif fname == "set_flags" and node.args \
                    and isinstance(node.args[0], ast.Dict):
                for k in node.args[0].keys:
                    s = _const_str(k)
                    if s is not None:
                        key = s.removeprefix("FLAGS_")
                        project.flag_writes.setdefault(key, []) \
                            .append(site)
        return []

    def check_project(self, project):
        if not project.saw_registry_module:
            # partial scan (e.g. `lint tests/`): resolution/deadness
            # verdicts would all be noise without the registry in view
            return []
        out: List[Violation] = []
        for name, sites in sorted(project.flag_reads.items()):
            if name not in project.flag_defs:
                f, ln = sites[0]
                out.append(Violation(
                    f, ln, "FL001",
                    f"flag {name!r} is read but never defined — it "
                    "does not resolve in the registry (flags.py / any "
                    "define_flag site)"))
        for name, sites in sorted(project.flag_writes.items()):
            if name not in project.flag_defs:
                f, ln = sites[0]
                out.append(Violation(
                    f, ln, "FL001",
                    f"set_flags writes unknown flag {name!r} (would "
                    "raise KeyError at runtime)"))
        readme = project.readme_text()
        for name, (f, ln) in sorted(project.flag_defs.items()):
            live = [s for s in project.flag_reads.get(name, ())
                    if not s[0].startswith("tests/")]
            if not live:
                out.append(Violation(
                    f, ln, "FL002",
                    f"dead flag {name!r}: defined but never read "
                    "outside tests/ — wire it or remove it"))
            if f"`{name}`" not in readme \
                    and f"PT_FLAGS_{name}" not in readme:
                out.append(Violation(
                    f, ln, "FL003",
                    f"flag {name!r} missing from README's flags "
                    "tables (document as `" + name + "` or "
                    f"PT_FLAGS_{name})"))
        return out


# ---------------------------------------------------------------------------
# OBS — observability completeness
# ---------------------------------------------------------------------------
class OBS001ProgramLabelCompleteness(Rule):
    """Collector + one project-level verdict: every compiled serving
    program registered in a ``TRACE_COUNTS`` compile counter must also
    carry a timing label in
    ``observability/profiling.PROGRAM_LABELS`` — the attribution
    registry the per-program device-time profiler and the recompile
    watchdog report against. A new jitted program that bumps a
    compile counter (TS002 forces that) but skips the label registry
    would compile, count and recompile INVISIBLY to the measurement
    layer; this closes the loop statically, like FL003 does for the
    README flags tables."""

    id = "OBS001"
    doc = ("every TRACE_COUNTS-registered program name must carry a "
           "timing label in observability/profiling.PROGRAM_LABELS")

    _PROFILING = "paddle_tpu/observability/profiling.py"

    def applies(self, relpath):
        return _in(relpath, "paddle_tpu")

    def check_module(self, project, tree, src, relpath):
        del src
        if relpath == self._PROFILING:
            project.saw_profiling_module = True
            for node in ast.walk(tree):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target]
                           if isinstance(node, ast.AnnAssign) else [])
                if any(isinstance(t, ast.Name)
                       and t.id == "PROGRAM_LABELS" for t in targets) \
                        and isinstance(node.value, ast.Dict):
                    for k in node.value.keys:
                        s = _const_str(k)
                        if s is not None:
                            project.program_labels.add(s)
        for node in ast.walk(tree):
            if (isinstance(node, ast.AugAssign)
                    and isinstance(node.target, ast.Subscript)
                    and isinstance(node.target.value, ast.Name)
                    and node.target.value.id == "TRACE_COUNTS"):
                name = _const_str(node.target.slice)
                if name is not None \
                        and name not in project.trace_programs:
                    project.trace_programs[name] = (relpath,
                                                    node.lineno)
        return []

    def check_project(self, project):
        if not project.saw_profiling_module:
            # partial scan (e.g. `lint tests/`): without the label
            # registry in view every program would read unlabeled
            return []
        out: List[Violation] = []
        for name, (f, ln) in sorted(project.trace_programs.items()):
            if name not in project.program_labels:
                out.append(Violation(
                    f, ln, "OBS001",
                    f"compiled program {name!r} bumps TRACE_COUNTS "
                    "but has no timing label in observability/"
                    "profiling.PROGRAM_LABELS — the per-program "
                    "profiler and the recompile watchdog cannot "
                    "attribute it"))
        return out


class PA001ProgramContractCompleteness(Rule):
    """Collector + one project-level verdict: every compiled serving
    program registered in a ``TRACE_COUNTS`` compile counter must also
    carry a contract in
    ``analysis/program_audit.PROGRAM_CONTRACTS`` — the declarative
    registry the jaxpr auditor (ptaudit) traces and enforces. OBS001
    guarantees a new program joins the *measurement* surface; this
    guarantees it joins the *audit* surface, so a program cannot ship
    without stating its donation/dtype/dead-operand promises. The
    runtime twin (tests/test_program_audit.py) pins the AST view
    against the imported registry."""

    id = "PA001"
    doc = ("every TRACE_COUNTS-registered program name must carry a "
           "contract in analysis/program_audit.PROGRAM_CONTRACTS")

    def applies(self, relpath):
        return _in(relpath, "paddle_tpu")

    def check_module(self, project, tree, src, relpath):
        del src
        # any program_audit.py under an analysis/ dir: the real
        # module plus synthetic tmp-repo twins the rule tests plant
        if relpath.endswith("analysis/program_audit.py"):
            project.saw_audit_module = True
            for node in ast.walk(tree):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target]
                           if isinstance(node, ast.AnnAssign) else [])
                if any(isinstance(t, ast.Name)
                       and t.id == "PROGRAM_CONTRACTS"
                       for t in targets) \
                        and isinstance(node.value, ast.Dict):
                    for k in node.value.keys:
                        s = _const_str(k)
                        if s is not None:
                            project.program_contracts.add(s)
        # TRACE_COUNTS bumps accumulate in project.trace_programs via
        # OBS001's collector (same scope, same walk) — no second scan
        return []

    def check_project(self, project):
        if not project.saw_audit_module:
            # partial scan (e.g. `lint tests/`): without the contract
            # registry in view every program would read uncontracted
            return []
        out: List[Violation] = []
        for name, (f, ln) in sorted(project.trace_programs.items()):
            if name not in project.program_contracts:
                out.append(Violation(
                    f, ln, "PA001",
                    f"compiled program {name!r} bumps TRACE_COUNTS "
                    "but has no contract in analysis/program_audit."
                    "PROGRAM_CONTRACTS — ptaudit cannot verify its "
                    "donation/dtype/transfer promises"))
        return out


class OBS002AlertRuleRegistry(Rule):
    """Collector + one project-level verdict: every alert-rule
    implementation in ``observability/alerts.py`` (a class deriving
    from ``AlertRule`` with a class-level ``name = "..."``) must
    appear in the canonical ``ALERT_RULES`` registry AND in the README
    alerts table. A detector that skips the registry fails at
    ``AlertManager`` construction anyway (the runtime twin), but one
    that skips the README would fire alerts no operator runbook
    names — the FL003 shape, applied to alerting."""

    id = "OBS002"
    doc = ("every AlertRule implementation must appear in "
           "observability/alerts.ALERT_RULES and in the README "
           "alerts table")

    def applies(self, relpath):
        # any alerts.py under an observability/ dir: the real module
        # plus synthetic tmp-repo twins the rule tests plant
        return relpath.endswith("observability/alerts.py")

    def check_module(self, project, tree, src, relpath):
        del src
        project.saw_alerts_module = True
        for node in ast.walk(tree):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target]
                       if isinstance(node, ast.AnnAssign) else [])
            if any(isinstance(t, ast.Name) and t.id == "ALERT_RULES"
                   for t in targets) \
                    and isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    s = _const_str(k)
                    if s is not None:
                        project.alert_rules.add(s)
        # module-level ClassDefs in source order, tracking the
        # transitive AlertRule hierarchy (an intermediate shape class
        # like _RatioCollapse makes its children rules too)
        known_bases = {"AlertRule"}
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            bases = {getattr(b, "id", None) or getattr(b, "attr", None)
                     for b in node.bases}
            if not bases & known_bases:
                continue
            known_bases.add(node.name)
            for stmt in node.body:
                # both spellings count: name = "x" and name: str = "x"
                # (the module-level ALERT_RULES scan above handles
                # AnnAssign the same way)
                if isinstance(stmt, ast.Assign):
                    targets = stmt.targets
                elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                    targets = [stmt.target]
                else:
                    continue
                for t in targets:
                    if isinstance(t, ast.Name) and t.id == "name":
                        s = _const_str(stmt.value)
                        if s:  # the base's name = "" is not a rule
                            project.alert_impls.setdefault(
                                s, (relpath, stmt.lineno))
        return []

    def check_project(self, project):
        if not project.saw_alerts_module:
            # partial scan: without the registry module in view every
            # rule would read unregistered — silent, like FL001
            return []
        readme = project.readme_text()
        out: List[Violation] = []
        for name, (f, ln) in sorted(project.alert_impls.items()):
            if name not in project.alert_rules:
                out.append(Violation(
                    f, ln, "OBS002",
                    f"alert rule {name!r} is implemented but missing "
                    "from the canonical ALERT_RULES registry — "
                    "register it (AlertManager rejects unregistered "
                    "rules at runtime too)"))
            if f"`{name}`" not in readme:
                out.append(Violation(
                    f, ln, "OBS002",
                    f"alert rule {name!r} missing from README's "
                    f"alerts table (document as `{name}`)"))
        return out


# ---------------------------------------------------------------------------
# CC — concurrency: copy-on-read snapshots, scheduler-owned mutation
# ---------------------------------------------------------------------------
_FRESH, _SHALLOW, _TAINTED = 0, 1, 2
_COPY_FUNCS = {"list", "tuple", "sorted", "set", "frozenset"}
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "pop", "popleft", "popitem", "remove", "clear",
             "move_to_end", "update", "add", "discard", "setdefault",
             "sort", "reverse", "rotate"}
_VIEW_ATTRS = {"items", "keys", "values", "get"}


class CC001CopyOnRead(Rule):
    id = "CC001"
    doc = ("scrape-thread reader methods (snapshot/backpressure) must "
           "iterate copies of scheduler-owned structures — wrap in "
           "list(...) (CC001) and never mutate them (CC002)")

    _READER_NAMES = {"backpressure", "_tel_state", "snapshot"}

    def applies(self, relpath):
        return _in(relpath, "paddle_tpu/inference")

    def _is_reader(self, name: str) -> bool:
        return name in self._READER_NAMES or name.endswith("_snapshot")

    def check_module(self, project, tree, src, relpath):
        del project, src
        out: List[Violation] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            methods = {n.name: n for n in node.body
                       if isinstance(n, ast.FunctionDef)}
            # sanitizer-bearing classes (the engine): every reader
            # must carry its runtime thread-ownership hook, so the
            # static rule guarantees the hook and the sanitizer's
            # SAFE_READS registration check can actually fire (CC003)
            sanitized_class = any(
                isinstance(n, ast.Attribute) and n.attr == "_san"
                for m in methods.values() for n in ast.walk(m))
            for name, fd in methods.items():
                if not self._is_reader(name):
                    continue
                if sanitized_class and not self._has_check_read(fd, name):
                    out.append(Violation(
                        relpath, fd.lineno, "CC003",
                        f"reader `{name}` lacks its sanitizer hook — "
                        f"call self._san.check_read({name!r}) (guarded "
                        "by `is not None`) so a foreign-thread caller "
                        "is checked against SAFE_READS at runtime"))
                out.extend(self._check_fn(fd, name))
                # one level of self-call expansion: a reader leaning on
                # a helper inherits the helper's races
                for callee in self._self_calls(fd):
                    sub = methods.get(callee)
                    if sub is not None and not self._is_reader(callee):
                        out.extend(self._check_fn(
                            sub, f"{callee} (called from reader "
                            f"{name})"))
        return out

    @staticmethod
    def _has_check_read(fd: ast.FunctionDef, name: str) -> bool:
        for n in ast.walk(fd):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "check_read" and n.args
                    and _const_str(n.args[0]) == name):
                return True
        return False

    def _self_calls(self, fd: ast.FunctionDef) -> Set[str]:
        out = set()
        for n in ast.walk(fd):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "self"):
                out.add(n.func.attr)
        return out

    # -------- taint machine --------
    def _check_fn(self, fd: ast.FunctionDef, ctx: str) -> List[Violation]:
        out: List[Violation] = []
        env: Dict[str, int] = {}

        def state(expr) -> int:
            if isinstance(expr, ast.Name):
                if expr.id == "self":
                    return _TAINTED
                return env.get(expr.id, _FRESH)
            if isinstance(expr, (ast.Attribute, ast.Subscript)):
                return _TAINTED if state(expr.value) >= _SHALLOW \
                    else _FRESH
            if isinstance(expr, ast.Call):
                f = expr.func
                arg_states = [state(a) for a in expr.args] + \
                    [state(k.value) for k in expr.keywords]
                if isinstance(f, ast.Name) and f.id in _COPY_FUNCS \
                        | {"dict"}:
                    return _SHALLOW if any(
                        s >= _SHALLOW for s in arg_states) else _FRESH
                if isinstance(f, ast.Attribute):
                    recv = state(f.value)
                    if f.attr in _VIEW_ATTRS:
                        # dict views / .get alias the live interior
                        return _TAINTED if recv >= _SHALLOW else _FRESH
                    # other method results: computed values, fresh-ish
                    if recv >= _SHALLOW or any(
                            s >= _SHALLOW for s in arg_states):
                        return _SHALLOW
                    return _FRESH
                return _SHALLOW if any(
                    s >= _SHALLOW for s in arg_states) else _FRESH
            if isinstance(expr, (ast.ListComp, ast.SetComp, ast.DictComp,
                                 ast.GeneratorExp)):
                return _SHALLOW
            if isinstance(expr, ast.IfExp):
                return max(state(expr.body), state(expr.orelse))
            if isinstance(expr, (ast.Dict, ast.List, ast.Tuple, ast.Set,
                                 ast.Constant, ast.BinOp, ast.BoolOp,
                                 ast.Compare, ast.UnaryOp, ast.JoinedStr)):
                return _FRESH
            return _FRESH

        def root_state(target) -> int:
            node = target
            while isinstance(node, (ast.Attribute, ast.Subscript)):
                node = node.value
            return state(node)

        def check_iter(it, line_node):
            if state(it) == _TAINTED:
                out.append(Violation(
                    "", line_node.lineno, "CC001",
                    f"reader `{ctx}` iterates live scheduler state — "
                    "snapshot it first (the copy-on-read pattern: "
                    "`list(x.items())`)"))

        def check_expr(expr):
            for n in ast.walk(expr):
                if isinstance(n, ast.Call):
                    f = n.func
                    if isinstance(f, ast.Name) and f.id == "dict" \
                            and n.args and state(n.args[0]) == _TAINTED:
                        out.append(Violation(
                            "", n.lineno, "CC001",
                            f"reader `{ctx}` copies a live dict with "
                            "dict(...) — iterate a list() copy instead "
                            "(`{k: v for k, v in list(x.items())}`)"))
                    elif isinstance(f, ast.Attribute) \
                            and f.attr in _MUTATORS \
                            and state(f.value) == _TAINTED:
                        out.append(Violation(
                            "", n.lineno, "CC002",
                            f"reader `{ctx}` mutates scheduler-owned "
                            f"state (.{f.attr}) — readers must be "
                            "pure; mutation belongs to engine methods "
                            "on the scheduler thread"))
                elif isinstance(n, (ast.ListComp, ast.SetComp,
                                    ast.DictComp, ast.GeneratorExp)):
                    for gen in n.generators:
                        check_iter(gen.iter, n)

        def walk(stmts):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue  # nested defs: separate execution context
                if isinstance(stmt, ast.Assign):
                    check_expr(stmt.value)
                    val = state(stmt.value)
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            env[t.id] = val
                        elif isinstance(t, (ast.Tuple, ast.List)):
                            inner = _TAINTED if val == _TAINTED else (
                                _TAINTED if val == _SHALLOW else _FRESH)
                            for e in t.elts:
                                if isinstance(e, ast.Name):
                                    env[e.id] = inner
                        elif isinstance(t, (ast.Attribute, ast.Subscript)):
                            if root_state(t) == _TAINTED:
                                out.append(Violation(
                                    "", stmt.lineno, "CC002",
                                    f"reader `{ctx}` writes scheduler-"
                                    "owned state — readers must be "
                                    "pure"))
                elif isinstance(stmt, ast.AugAssign):
                    check_expr(stmt.value)
                    if isinstance(stmt.target,
                                  (ast.Attribute, ast.Subscript)) \
                            and root_state(stmt.target) == _TAINTED:
                        out.append(Violation(
                            "", stmt.lineno, "CC002",
                            f"reader `{ctx}` mutates scheduler-owned "
                            "state in place"))
                elif isinstance(stmt, ast.For):
                    check_expr(stmt.iter)
                    check_iter(stmt.iter, stmt)
                    it = state(stmt.iter)
                    inner = _TAINTED if it >= _SHALLOW else _FRESH
                    for e in ast.walk(stmt.target):
                        if isinstance(e, ast.Name):
                            env[e.id] = inner
                    walk(stmt.body)
                    walk(stmt.body)  # loop-carried taint: second pass
                    walk(stmt.orelse)
                elif isinstance(stmt, ast.While):
                    check_expr(stmt.test)
                    walk(stmt.body)
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, ast.If):
                    check_expr(stmt.test)
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, (ast.Expr, ast.Return)):
                    if stmt.value is not None:
                        check_expr(stmt.value)
                elif isinstance(stmt, ast.With):
                    for item in stmt.items:
                        check_expr(item.context_expr)
                    walk(stmt.body)
                elif isinstance(stmt, ast.Try):
                    walk(stmt.body)
                    for h in stmt.handlers:
                        walk(h.body)
                    walk(stmt.orelse)
                    walk(stmt.finalbody)
                elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                    check_expr(stmt.value)
                    if isinstance(stmt.target, ast.Name):
                        env[stmt.target.id] = state(stmt.value)

        walk(fd.body)
        return out


ALL_RULES: Sequence[Rule] = (
    TS001HostSyncInJit(),
    TS002TraceCountRegistration(),
    TS003JitInLoop(),
    DT001StdlibRandom(),
    DT002GlobalNumpyRandom(),
    DT003WallClock(),
    FlagsHygiene(),
    OBS001ProgramLabelCompleteness(),
    OBS002AlertRuleRegistry(),
    PA001ProgramContractCompleteness(),
    CC001CopyOnRead(),
)

RULE_DOCS: Dict[str, str] = {
    "TS001": TS001HostSyncInJit.doc,
    "TS002": TS002TraceCountRegistration.doc,
    "TS003": TS003JitInLoop.doc,
    "DT001": DT001StdlibRandom.doc,
    "DT002": DT002GlobalNumpyRandom.doc,
    "DT003": DT003WallClock.doc,
    "FL001": "flag reads/writes must resolve in the canonical registry",
    "FL002": "defined flags must be read somewhere outside tests/",
    "FL003": "defined flags must appear in README's flags tables",
    "OBS001": OBS001ProgramLabelCompleteness.doc,
    "OBS002": OBS002AlertRuleRegistry.doc,
    "PA001": PA001ProgramContractCompleteness.doc,
    "CC001": "scrape-thread readers iterate copies (list(...)-wrapped)",
    "CC002": "scrape-thread readers never mutate scheduler-owned state",
    "CC003": ("readers on sanitizer-bearing classes carry their "
              "check_read hook (closes the SAFE_READS loop)"),
}
