"""Runtime invariant sanitizer for the continuous-batching engine.

The static half of this PR (ptlint) proves properties of the *code*;
this module checks properties of the *state* the scheduler actually
builds, once per tick, behind ``PT_FLAGS_sanitize``:

* **page/refcount conservation** (paged mode): every pool page is
  exactly one of {free, referenced}; each page's refcount equals its
  recounted owners (slots holding it in their block tables + the
  prefix store's retain); the free list has no duplicates; the
  reserved sink page is out of circulation; the ``shared_pages``
  fast-path counter agrees with a full recount.
* **slot-heap agreement**: the free heap and the active mask partition
  the slots; ``_slot_req`` holds exactly the active slots.
* **seq_len bounds + host-truth agreement**: inactive slots sit at 0;
  active slots fit ``max_len`` (paged: their allocated pages), and
  match the host-side token ledger — ``prefill_ids + generated - 1``
  (the first token is sampled by prefill), which is exactly the state
  deterministic replay rebuilds from.
* **scale-pool shape agreement** (int8 caches): per-row dequant scale
  arrays mirror their payload pools block for block (paged:
  ``[kvh, n_pages, page_size, 1]``; contiguous ``QuantizedKV``:
  ``scale.shape == q.shape[:-1]``) — shape metadata only, never a
  device sync.
* **thread ownership**: ticks belong to ONE scheduler thread, and a
  foreign (metrics/scrape) thread may only enter readers registered
  copy-on-read-safe (``SAFE_READS`` — the same list ptlint's CC rules
  keep honest statically).

Every hook in ``serving.py`` is a single ``if self._san is not None``
identity check when the flag is off (the telemetry=off pattern; pinned
by test). Violations raise :class:`SanitizerError` naming the violated
invariant and the site. All checks are host bookkeeping — O(slots +
pages) python, zero compiled programs, zero device traffic.
"""

from __future__ import annotations

import threading
from typing import Optional

# reader methods registered copy-on-read-safe: a foreign thread may
# call these (and ONLY these) while the scheduler runs. Kept in sync
# with ptlint's CC reader set — adding a reader here without the
# list()-copy discipline is what the lint exists to catch, and CC003
# statically requires every engine reader to carry its check_read
# hook, so an unregistered reader cannot silently skip this check.
SAFE_READS = frozenset({
    "metrics_snapshot", "prefix_snapshot", "spec_snapshot",
    "slo_snapshot", "resilience_snapshot", "backpressure", "_tel_state",
    # multi-engine router readers (router.py) — same copy-on-read
    # contract, same CC001/CC002/CC003 static coverage
    "fleet_snapshot",
    # program-time attribution readers (PR 12): profiler stats,
    # recompile-watchdog state and HBM residency are copy-on-read
    # host metadata
    "profile_snapshot", "recompile_snapshot", "hbm_snapshot",
    # flight-data readers (PR 13): time-series windows are immutable
    # once appended (the ring copies under its lock), alert/cost
    # snapshots copy every nested structure
    "timeline_snapshot", "alerts_snapshot", "cost_snapshot",
    # seal-time contract-audit verdict (ptaudit): the report is
    # immutable after seal_programs(); the snapshot copies it
    "audit_snapshot",
    # multi-tenant accounting (serving front door): cumulative tenant
    # counters + live slot/page usage, copied per-call; pages_of reads
    # share _tel_state's staleness contract
    "tenant_snapshot",
})


class SanitizerError(AssertionError):
    """An engine invariant does not hold. ``invariant`` names the
    violated invariant class, ``site`` the hook that caught it."""

    def __init__(self, invariant: str, site: str, detail: str):
        self.invariant = invariant
        self.site = site
        super().__init__(
            f"[sanitize] invariant {invariant!r} violated at "
            f"{site!r}: {detail}")


class EngineSanitizer:
    """Per-engine invariant checker (constructed only when
    ``PT_FLAGS_sanitize`` is on — the engine holds None otherwise)."""

    def __init__(self, engine=None):
        del engine  # checks read the engine per-call; no cycle held
        self._owner: Optional[int] = None
        # failover count at the last full owner-map sweep (fleet
        # checks only; terminal-entry resolution is failover-gated)
        self._fleet_failovers_swept = -1

    # ---------------- thread ownership ----------------
    def note_tick(self, site: str):
        """Called at every scheduler-tick entry: the first ticking
        thread owns the engine; a second thread ticking it is exactly
        the race the scheduler contract forbids."""
        tid = threading.get_ident()
        if self._owner is None:
            self._owner = tid
        elif tid != self._owner:
            raise SanitizerError(
                "scheduler-ownership", site,
                f"tick from thread {tid} but the engine is owned by "
                f"scheduler thread {self._owner} — one thread drives "
                "step()/step_chunk()/drain()")

    def check_read(self, name: str):
        """Called at reader entries: a foreign thread may only use the
        registered copy-on-read-safe readers."""
        if self._owner is None:
            return
        tid = threading.get_ident()
        if tid != self._owner and name not in SAFE_READS:
            raise SanitizerError(
                "thread-ownership", name,
                f"read of unlocked scheduler state from foreign thread "
                f"{tid} (owner {self._owner}); register the method in "
                "analysis.sanitizer.SAFE_READS only once it follows "
                "the copy-on-read pattern (ptlint CC001/CC002)")

    # ---------------- per-tick state invariants ----------------
    def check_tick(self, engine, site: str = "tick"):
        self._check_slots(engine, site)
        if engine.pool is not None:
            self._check_pool(engine, site)
            self._check_block_tables(engine, site)
        self._check_scale_shapes(engine, site)

    # -- slot heap / active mask / request map / seq_len bounds --
    def _check_slots(self, engine, site):
        cfg = engine.cfg
        heap = list(engine._free_heap)
        free = set(heap)
        if len(free) != len(heap):
            raise SanitizerError(
                "slot-heap", site,
                f"duplicate slots in the free heap: {sorted(heap)}")
        active = {s for s in range(cfg.max_slots) if engine.active[s]}
        if free & active:
            raise SanitizerError(
                "slot-heap", site,
                f"slots {sorted(free & active)} are both free and "
                "active")
        if free | active != set(range(cfg.max_slots)):
            missing = set(range(cfg.max_slots)) - free - active
            raise SanitizerError(
                "slot-heap", site,
                f"slots {sorted(missing)} are neither free nor active "
                "(leaked from the heap)")
        if set(engine._slot_req) != active:
            raise SanitizerError(
                "slot-heap", site,
                f"_slot_req keys {sorted(engine._slot_req)} != active "
                f"slots {sorted(active)}")
        for s in range(cfg.max_slots):
            L = int(engine.seq_lens[s])
            if s not in active:
                if L != 0:
                    raise SanitizerError(
                        "seq-len", site,
                        f"inactive slot {s} has seq_len {L} (expect 0)")
                continue
            if not 0 <= L <= cfg.max_len:
                raise SanitizerError(
                    "seq-len", site,
                    f"slot {s} seq_len {L} outside [0, {cfg.max_len}]")
            if engine.pool is not None:
                cap = len(engine.pool.pages_of[s]) * cfg.page_size
                if L > cap:
                    raise SanitizerError(
                        "seq-len", site,
                        f"slot {s} seq_len {L} exceeds its "
                        f"{len(engine.pool.pages_of[s])} allocated "
                        f"page(s) = {cap} tokens")
            req = engine._slot_req[s]
            expect = req.prompt.size + len(req.output) - 1
            if req.output and L != expect:
                raise SanitizerError(
                    "seq-len", site,
                    f"slot {s} (rid {req.rid}) seq_len {L} != host "
                    f"token ledger prompt({req.prompt.size}) + "
                    f"output({len(req.output)}) - 1 = {expect} — the "
                    "cache and the replay source of truth disagree")

    # -- page/refcount conservation --
    def _check_pool(self, engine, site):
        pool = engine.pool
        sink = 1 if getattr(pool, "reserve_sink", False) else 0
        free = list(pool._free)
        if len(set(free)) != len(free):
            raise SanitizerError(
                "page-conservation", site,
                "duplicate page ids on the free list")
        if sink and (0 in set(free) or pool.ref.get(0, 0) > 0):
            raise SanitizerError(
                "page-conservation", site,
                "reserved sink page 0 re-entered circulation")
        owners = {}
        for s, pages in list(pool.pages_of.items()):
            for p in pages:
                owners[p] = owners.get(p, 0) + 1
        store = engine._prefix
        if engine.cfg.paged and store is not None:
            # entries are (page id, namespace) — the retain is on the
            # page regardless of which tenant published it
            for p, _ns in list(getattr(store, "_blocks", {}).values()):
                owners[p] = owners.get(p, 0) + 1
        for p, n in sorted(owners.items()):
            if pool.ref.get(p, 0) != n:
                raise SanitizerError(
                    "page-conservation", site,
                    f"page {p} refcount {pool.ref.get(p, 0)} != "
                    f"recounted owners {n} (slots holding it + prefix-"
                    "store retain) — a leak or double-free in the "
                    "making")
        for p, n in sorted(pool.ref.items()):
            if n <= 0:
                raise SanitizerError(
                    "page-conservation", site,
                    f"page {p} carries non-positive refcount {n}")
            if owners.get(p, 0) != n:
                raise SanitizerError(
                    "page-conservation", site,
                    f"page {p} refcount {n} has only "
                    f"{owners.get(p, 0)} recounted owner(s)")
        freeset = set(free)
        if freeset & set(pool.ref):
            both = sorted(freeset & set(pool.ref))
            raise SanitizerError(
                "page-conservation", site,
                f"pages {both} are both free and referenced")
        if len(free) + len(pool.ref) != pool.n_pages - sink:
            raise SanitizerError(
                "page-conservation", site,
                f"free({len(free)}) + referenced({len(pool.ref)}) != "
                f"n_pages({pool.n_pages}) - sink({sink}) — pages "
                "leaked out of both ledgers")
        shared = sum(1 for n in pool.ref.values() if n > 1)
        if shared != pool.shared_pages:
            raise SanitizerError(
                "page-conservation", site,
                f"shared_pages fast-path counter {pool.shared_pages} "
                f"!= recount {shared} — the decode COW guard would "
                "skip its scan while pages are shared")

    # -- block table mirrors pages_of --
    def _check_block_tables(self, engine, site):
        pool = engine.pool
        for s in range(pool.slots):
            pages = pool.pages_of[s]
            row = pool.block_tables[s]
            for i, p in enumerate(pages):
                if int(row[i]) != int(p):
                    raise SanitizerError(
                        "block-table", site,
                        f"slot {s} block_tables[{i}] = {int(row[i])} "
                        f"but pages_of lists page {p}")
            for i in range(len(pages), pool.max_pages_per_slot):
                if int(row[i]) != 0:
                    raise SanitizerError(
                        "block-table", site,
                        f"slot {s} block_tables[{i}] = {int(row[i])} "
                        "past its allocation (expect the sink id 0)")

    # ---------------- fleet invariants (router) ----------------
    def check_fleet(self, router, site: str = "fleet-tick"):
        """The ROUTER-level invariant cross-replica failover must
        preserve: every request id is owned by EXACTLY one place —
        the router's own admission queue, ONE replica's queue, or ONE
        replica's active slot — and a finished rid is never
        simultaneously live anywhere. Dual ownership is precisely
        what a buggy failover produces (the dead replica keeps a rid
        its reclaim also re-admitted elsewhere: two engines then
        decode the same request and its ledger forks). Also checks
        the router's owner map: every LIVE rid's entry points at the
        replica that actually holds it (per tick, O(live)), and —
        after any failover mutated the map — every TERMINAL entry
        resolves to a finish registry on the replica it names."""
        owners: dict = {}
        held_by: dict = {}  # rid -> replica idx actually holding it

        def note(rid, where, idx=None):
            owners.setdefault(rid, []).append(where)
            if idx is not None:
                held_by[rid] = idx

        for req in list(router._queue):
            note(req.rid, "router-queue")
        for rep in list(router._replicas):
            eng = rep.engine
            for req in list(eng._queue):
                note(req.rid, f"replica{rep.idx}-queue", rep.idx)
            for req in list(eng._slot_req.values()):
                note(req.rid, f"replica{rep.idx}-slot", rep.idx)
        for rid, places in sorted(owners.items()):
            if len(places) > 1:
                raise SanitizerError(
                    "rid-ownership", site,
                    f"rid {rid} is owned by {len(places)} places at "
                    f"once: {places} — failover must MOVE a request, "
                    "never copy it")
        # finished-vs-live and owner-map resolution run as O(1) dict
        # membership probes against the finish registries: rebuilding
        # a set of every rid the fleet EVER finished would cost
        # O(total completed) per tick — quadratic over a sanitized
        # soak — to answer questions about the handful of live rids
        replicas = list(router._replicas)

        def finished_at(rid):
            if rid in router._finished:
                return "router"
            for rep in replicas:
                if rid in rep.engine._finished:
                    return f"replica{rep.idx}"
            return None

        for rid, places in sorted(owners.items()):
            where = finished_at(rid)
            if where is not None:
                raise SanitizerError(
                    "rid-ownership", site,
                    f"rid {rid} is finished ({where}) AND still live "
                    f"({places}) — a finished request must have left "
                    "every queue and slot")
        # live owner agreement, O(live): a replica-held rid must have
        # an owner entry pointing at the replica that holds it; a
        # router-held rid must have NONE (queued rids are absent from
        # the map by design — _reclaim pops before re-queueing)
        for rid, holder in sorted(held_by.items()):
            ridx = router._owner.get(rid)
            if ridx is None:
                raise SanitizerError(
                    "rid-ownership", site,
                    f"rid {rid} is held by replica {holder} but "
                    "absent from the router owner map — "
                    "result()/cancel() cannot find it")
            if ridx != holder:
                raise SanitizerError(
                    "rid-ownership", site,
                    f"router owner map routes rid {rid} to "
                    f"replica {ridx} but replica {holder} holds "
                    "it — result()/cancel() would misroute")
        for rid in owners:
            if rid not in held_by and rid in router._owner:
                raise SanitizerError(
                    "rid-ownership", site,
                    f"rid {rid} sits in the router hold queue but the "
                    f"owner map routes it to replica "
                    f"{router._owner[rid]} — cancel() would misroute")
        # full owner-map resolution sweep (every TERMINAL entry
        # resolves to a finish registry on the replica the map names)
        # only after a FAILOVER mutated the map: placement only
        # appends live entries (vetted above) and finish registries
        # never shrink, so between failovers the sweep is a no-op —
        # running it per tick would cost O(total completed) per tick,
        # quadratic over a sanitized soak
        n_failovers = router.fleet_stats["failovers"]
        if n_failovers == self._fleet_failovers_swept:
            return
        self._fleet_failovers_swept = n_failovers
        for rid in list(router._finished):
            fin = next((rep.idx for rep in replicas
                        if rid in rep.engine._finished), None)
            if fin is not None:
                raise SanitizerError(
                    "rid-ownership", site,
                    f"rid {rid} is finished at the router AND on "
                    f"replica {fin} — a request must reach exactly "
                    "one terminal registry")
        for rid, ridx in list(router._owner.items()):
            if rid in held_by:
                continue  # live: vetted against its holder above
            if rid in replicas[ridx].engine._finished:
                # terminal exactly where the map says — but it must
                # be terminal exactly ONCE: a second registry holding
                # the same rid is double accounting (a reclaim that
                # timed a victim out on the dead replica while the
                # survivor also finished its replay)
                dup = next(
                    (f"replica{rep.idx}" for rep in replicas
                     if rep.idx != ridx and rid in rep.engine._finished),
                    "router" if rid in router._finished else None)
                if dup is not None:
                    raise SanitizerError(
                        "rid-ownership", site,
                        f"rid {rid} is finished on replica {ridx} "
                        f"AND on {dup} — a request must reach exactly "
                        "one terminal registry")
                continue
            fin = next((rep.idx for rep in replicas
                        if rid in rep.engine._finished), None)
            if fin is None:
                raise SanitizerError(
                    "rid-ownership", site,
                    f"router owner map routes rid {rid} to replica "
                    f"{ridx}, but no replica holds or finished it — "
                    "the request leaked out of the fleet")
            raise SanitizerError(
                "rid-ownership", site,
                f"rid {rid} finished on replica {fin} but the "
                f"owner map says replica {ridx} — result() would "
                "return None forever")

    # -- int8 scale pools mirror their payload --
    def _check_scale_shapes(self, engine, site):
        from ..inference.paged import QuantizedKV

        if engine.pool is not None:
            for li, c in enumerate(engine.layer_caches):
                if (c.k_scale is None) != (c.v_scale is None):
                    raise SanitizerError(
                        "scale-pool", site,
                        f"layer {li}: k_scale/v_scale presence differs")
                if c.k_scale is None:
                    continue
                want = tuple(c.k_pages.shape[:3]) + (1,)
                for nm, scale, pages in (("k", c.k_scale, c.k_pages),
                                         ("v", c.v_scale, c.v_pages)):
                    if tuple(scale.shape) != want:
                        raise SanitizerError(
                            "scale-pool", site,
                            f"layer {li} {nm}_scale shape "
                            f"{tuple(scale.shape)} desynced from pool "
                            f"{tuple(pages.shape)} (want {want}) — "
                            "dequant state no longer travels with the "
                            "page")
            return
        for li, (k, v) in enumerate(engine.caches):
            for nm, c in (("k", k), ("v", v)):
                if isinstance(c, QuantizedKV):
                    want = tuple(c.q.shape[:-1])
                    if tuple(c.scale.shape) != want:
                        raise SanitizerError(
                            "scale-pool", site,
                            f"layer {li} contiguous {nm} scale shape "
                            f"{tuple(c.scale.shape)} != q rows {want}")
