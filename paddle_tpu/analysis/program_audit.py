"""ptaudit — jaxpr-level contract auditor for the compiled serving
program set.

ptlint (analysis/lint.py) checks the Python SOURCE and the sanitizer
checks runtime STATE; this module checks the *traced programs
themselves*. Every modeled perf claim in the bench ledger rests on
trace-level promises — in-place KV append via donation, int8/bf16
streams staying narrow until in-kernel dequant, no host transfers
inside a dispatch, a stable program size — and none of those is
visible to an AST scan or a state invariant. ptaudit traces each
program at small CPU-friendly shapes (the same tiny-engine helpers the
serving test suites use — ``tests/serving_utils.py`` imports them from
here) and walks the resulting jaxpr, enforcing one declarative
:data:`PROGRAM_CONTRACTS` entry per ``TRACE_COUNTS`` /
``PROGRAM_LABELS`` program name. ptlint's **PA001** rule keeps that
registry complete, the same shape as OBS001 for timing labels.

Rule families::

    AL001  a contract pool operand is not donated (input/output
           aliasing dropped -> a full pool copy per dispatch)
    AL002  a donated operand the contract does not declare (registry
           drift: the contract must mirror the program)
    DQ001  a narrow value stream (bf16/f16/int8/int4) widens at a
           dtype pair the contract does not allowlist
    DQ002  an allowlisted widening pair's count grew past the
           committed baseline (a new upcast site crept in)
    TX001  host callback/transfer primitive inside a serving program
           (io_callback/pure_callback/debug_callback/infeed/outfeed)
    DD001  dead input leaf the contract's ``dead_ok`` does not cover
    DD002  passthrough or constant output (costs a donation slot /
           a dispatch-time copy for nothing)
    DD003  unused trace constant captured into the program
    SZ001  program op-count grew past the committed baseline
    SZ002  program missing from the committed baseline

Usage::

    python -m paddle_tpu.analysis.audit                 # full repo set
    python -m paddle_tpu.analysis.audit --arms paged-int8 --json
    python -m paddle_tpu.analysis.audit --rules
    python -m paddle_tpu.analysis.audit --write-baseline

Exit status mirrors ptlint: 0 clean, 1 on any violation, 2 on usage
errors. The committed baseline (``.ptaudit-baseline.json``) records
per ``arm::program`` op counts and allowlisted-widening counts — the
CPU-backend trace is canonical (tier-1 runs ``JAX_PLATFORMS=cpu``; on
TPU the fused Pallas kernels change the op mix, so refresh locally
with ``--write-baseline`` before comparing there). Unlike ptlint's
baseline, SHRINKING is also a mismatch (`--write-baseline` to ratchet
down): the committed counts are an exact pin, so program-size drift in
either direction is reviewable in the diff.

Production engines self-audit after warmup via
``PT_FLAGS_audit_on_seal`` (default off = one identity check):
``engine.seal_programs()`` runs the AL/DQ/TX/DD families against the
engine's OWN programs at its real shapes (SZ needs the canonical tiny
arms, so it stays with the CLI) and surfaces the verdict in
``metrics_snapshot()["audit"]``. Audits are trace-only — no compile,
no dispatch — and restore ``TRACE_COUNTS``/``TRACE_SHAPES``, so the
recompile watchdog and the tests' compile-count guards never see them.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import sys
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import flags
from ..kernels.decode_attention import AUDIT_WIDEN_ALLOW
from .lint import find_root

BASELINE_NAME = ".ptaudit-baseline.json"

RULE_DOCS: Dict[str, str] = {
    "AL001": "contract pool operands must be donated (in-place "
             "append / page-copy aliasing, verified structurally)",
    "AL002": "donated operands must be declared in the contract "
             "(the registry mirrors the program, both directions)",
    "DQ001": "narrow streams (bf16/f16/int8/int4) may widen only at "
             "allowlisted dtype pairs (softmax accumulators, "
             "scale-row dequant)",
    "DQ002": "allowlisted widening counts may not grow past the "
             "committed baseline (a new upcast site is a finding)",
    "TX001": "no host callbacks/transfers inside a serving program",
    "DD001": "no dead inputs beyond the contract's dead_ok "
             "(unused leaves still pay dispatch-time flattening)",
    "DD002": "no passthrough/constant outputs (each costs a donation "
             "slot or a device copy for nothing)",
    "DD003": "no unused trace constants captured into the program",
    "SZ001": "per-program op counts are pinned by the committed "
             "baseline (size creep is reviewable like ptlint's)",
    "SZ002": "every audited program must carry a baseline entry "
             "(--write-baseline)",
}


@dataclass
class AuditViolation:
    arm: str
    program: str
    rule: str
    message: str


class AuditError(RuntimeError):
    """A program could not be traced/analyzed at all — a broken probe
    or contract, never a contract *violation* (those report)."""


# ---------------------------------------------------------------------------
# contracts — one per TRACE_COUNTS / PROGRAM_LABELS program name
# (ptlint PA001 keeps this registry complete; the runtime twin in
# tests/test_program_audit.py pins it against PROGRAM_LABELS)
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class ProgramContract:
    #: cache modes the program exists in
    modes: Tuple[str, ...]
    #: top-level operand names whose EVERY leaf must be donated (AL)
    donate: Tuple[str, ...] = ()
    #: "src->dst" -> justification for a monitored widening pair (DQ)
    widen_allow: Mapping[str, str] = field(default_factory=dict)
    #: fnmatch patterns over leaf labels allowed to be dead (DD001)
    dead_ok: Tuple[str, ...] = ()
    #: fnmatch patterns over leaf labels allowed to pass through (DD002)
    passthrough_ok: Tuple[str, ...] = ()
    note: str = ""


# the static no-sampling arm keeps per-slot sampling params on the
# signature so both arms share one call site; greedy engine-global
# traces leave them (and the PRNG key) unused BY DESIGN
_GREEDY_DEAD = ("key", "samp*")
# contig mode: block tables ride the shared paged/contig signature as
# a [1] sentinel so the two modes keep one call-site shape
_BT_SENTINEL = ("bt",)

PROGRAM_CONTRACTS: Dict[str, ProgramContract] = {
    "prefill_chunk": ProgramContract(
        modes=("paged", "contig"),
        donate=("caches",),
        widen_allow=AUDIT_WIDEN_ALLOW,
        dead_ok=_GREEDY_DEAD + _BT_SENTINEL,
        note="THE [slots, C] chunked prefill: writes straight into "
             "the live global cache at per-slot offsets",
    ),
    "prefill_bucket": ProgramContract(
        modes=("paged", "contig"),
        donate=("caches",),
        widen_allow=AUDIT_WIDEN_ALLOW,
        dead_ok=_GREEDY_DEAD,
        note="legacy per-bucket prefill (the parity oracle) fills a "
             "fresh single-sequence bucket cache in place — the "
             "missing donation here was ptaudit's first real finding",
    ),
    "prefill_insert": ProgramContract(
        modes=("contig",),
        donate=("global_caches",),
        note="pure data movement: bucket cache -> slot rows; no "
             "compute, so no widening is allowed at all",
    ),
    "prefill_scatter": ProgramContract(
        modes=("paged",),
        donate=("layer_caches",),
        note="pure data movement: bucket cache -> the slot's pages",
    ),
    "prefix_insert": ProgramContract(
        modes=("contig",),
        donate=("global_caches",),
        note="cached prefix block -> slot rows (int8 blocks carry "
             "their scale rows; both insert via the same program)",
    ),
    "prefix_read": ProgramContract(
        modes=("contig",),
        donate=(),
        note="read-only: slices a slot's rows into the store's "
             "materialized block — donating would free live cache",
    ),
    "page_copy": ProgramContract(
        modes=("paged",),
        donate=("layer_caches",),
        note="copy-on-write page duplication; scale rows ride along "
             "— an undonated pool here is a full-pool copy per COW",
    ),
    "decode_step": ProgramContract(
        modes=("paged", "contig"),
        donate=("caches",),
        widen_allow=AUDIT_WIDEN_ALLOW,
        dead_ok=_GREEDY_DEAD,
        note="the [slots, 1] decode program (PR-3 in-place append "
             "promise, verified structurally here)",
    ),
    "decode_chunk": ProgramContract(
        modes=("paged", "contig"),
        donate=("caches",),
        widen_allow=AUDIT_WIDEN_ALLOW,
        dead_ok=_GREEDY_DEAD + _BT_SENTINEL,
        note="K-step fused decode (lax.scan); the scan carries the "
             "donated pool through every step on device",
    ),
    "spec_verify": ProgramContract(
        modes=("paged", "contig"),
        donate=("caches",),
        widen_allow=AUDIT_WIDEN_ALLOW,
        dead_ok=_GREEDY_DEAD + _BT_SENTINEL,
        note="the [slots, spec_k+1] verify pass appends every row's "
             "K/V in place; rollback is a host length decrement",
    ),
}


# ---------------------------------------------------------------------------
# tiny-engine helpers (shared with tests/serving_utils.py — ONE source
# of truth for the CPU-friendly shapes the audits and the serving
# suites trace at)
# ---------------------------------------------------------------------------
def tiny_model(seed: int = 0):
    """A tiny llama + its config, deterministically seeded."""
    import paddle_tpu as pt
    from ..models import LlamaConfig, LlamaForCausalLM

    pt.seed(seed)
    cfg = LlamaConfig.tiny()
    return LlamaForCausalLM(cfg), cfg


def tiny_engine_config(paged: bool, **kw):
    """The canonical tiny EngineConfig (2 slots, 128 max_len, 8-token
    pages) every serving test suite and audit arm builds on."""
    from ..inference.serving import EngineConfig

    kw.setdefault("max_slots", 2)
    kw.setdefault("max_len", 128)
    kw.setdefault("seq_buckets", (32,))
    kw.setdefault("cache_dtype", jnp.float32)
    kw.setdefault("page_size", 8)
    return EngineConfig(paged=paged, **kw)


# the canonical audit arms: both cache modes at bf16, plus the fully
# quantized paged arm (int8 weights x int8 KV — contig rejects int8
# pools at init, so there is no contig-int8 arm to audit)
AUDIT_ARMS: Dict[str, dict] = {
    "contig-bf16": dict(paged=False, cache_dtype=jnp.bfloat16),
    "paged-bf16": dict(paged=True, cache_dtype=jnp.bfloat16),
    "paged-int8": dict(paged=True, cache_dtype="int8",
                       weight_dtype="int8"),
}

# serving flags that shape the traced programs: pinned to their
# registry defaults for the audit arms so the committed baseline is
# reproducible regardless of ambient flag state (callers' flags are
# restored afterwards)
_PINNED_FLAGS = ("prefill_chunk", "fused_decode", "prefix_cache",
                 "spec_decode", "kv_cache_dtype", "serve_weight_dtype")


def build_audit_engine(arm: str, model=None):
    """Build the tiny engine for one canonical audit arm (the caller
    pins flags; :func:`audit_repo` does this for you)."""
    from ..inference.serving import ContinuousBatchingEngine

    if arm not in AUDIT_ARMS:
        raise AuditError(
            f"unknown audit arm {arm!r} (have {sorted(AUDIT_ARMS)})")
    if model is None:
        model, _ = tiny_model()
    return ContinuousBatchingEngine(
        model, tiny_engine_config(**AUDIT_ARMS[arm]))


# ---------------------------------------------------------------------------
# probes: representative example args per program, built from the
# engine's own shapes/state — tracing inputs only, nothing dispatches
# ---------------------------------------------------------------------------
@dataclass
class Probe:
    fn: object          # the engine's jitted wrapper
    args: tuple         # example args (static values included in place)
    static_argnums: Tuple[int, ...]
    argnames: Tuple[str, ...]  # names of the DYNAMIC args, in order


def _samp_vectors(n: int):
    return (jnp.zeros((n,), bool), jnp.ones((n,), jnp.float32),
            jnp.zeros((n,), jnp.int32), jnp.ones((n,), jnp.float32))


def _probe_common(eng):
    cfg = eng.cfg
    lens = jnp.zeros((cfg.max_slots,), jnp.int32)
    if cfg.paged:
        bt = jnp.asarray(eng.pool.block_tables)
        caches = eng.layer_caches
    else:
        bt = jnp.zeros((1,), jnp.int32)
        caches = eng.caches
    return lens, bt, caches, _samp_vectors(cfg.max_slots), \
        jax.random.PRNGKey(0)


def _probe_decode_step(eng):
    from ..inference.paged import PagedState

    lens, _bt, caches, samp, key = _probe_common(eng)
    toks = jnp.zeros((eng.cfg.max_slots, 1), jnp.int32)
    third = PagedState(block_tables=jnp.asarray(eng.pool.block_tables),
                       seq_lens=lens) if eng.cfg.paged else lens
    return Probe(eng._decode(),
                 (eng._pb, toks, caches, third, key, samp, False),
                 (6,), ("pb", "toks", "caches", "state_or_lens",
                        "key", "samp"))


def _probe_decode_chunk(eng):
    lens, bt, caches, samp, key = _probe_common(eng)
    slots = eng.cfg.max_slots
    toks = jnp.zeros((slots, 1), jnp.int32)
    active = jnp.zeros((slots,), bool)
    budget = jnp.zeros((slots,), jnp.int32)
    # K=2 keeps the scan trace tiny; the contract properties are
    # invariant to the (static) chunk length
    return Probe(eng._decode_n(),
                 (eng._pb, toks, caches, lens, active, budget, bt,
                  key, samp, 2, False),
                 (9, 10), ("pb", "toks", "caches", "lens", "active",
                           "budget", "bt", "key", "samp"))


def _probe_spec_verify(eng):
    lens, bt, caches, samp, key = _probe_common(eng)
    S = eng.cfg.spec_k + 1
    ids = jnp.zeros((eng.cfg.max_slots, S), jnp.int32)
    n_draft = jnp.zeros((eng.cfg.max_slots,), jnp.int32)
    return Probe(eng._verify(),
                 (eng._pb, ids, caches, bt, lens, n_draft, key, samp,
                  False),
                 (8,), ("pb", "ids", "caches", "bt", "start",
                        "n_draft", "key", "samp"))


def _probe_prefill_chunk(eng):
    if eng._chunk_len <= 0:
        # PT_FLAGS_prefill_chunk=0: the engine runs the legacy
        # per-bucket path and the [slots, C] program has no shape
        return "chunked prefill disabled (PT_FLAGS_prefill_chunk=0) " \
               "— the program never dispatches on this engine"
    lens, bt, caches, samp, key = _probe_common(eng)
    ids = jnp.zeros((eng.cfg.max_slots, eng._chunk_len), jnp.int32)
    last_idx = jnp.zeros((eng.cfg.max_slots,), jnp.int32)
    return Probe(eng._prefill_chunked(),
                 (eng._pb, ids, caches, bt, lens, last_idx, key, samp,
                  False),
                 (8,), ("pb", "ids", "caches", "bt", "start",
                        "last_idx", "key", "samp"))


_INT8_LEGACY_SKIP = ("legacy prefill path is rejected at init for "
                     "int8 pools — the program can never run in "
                     "this arm")


def _legacy_prefill_blocked(eng) -> bool:
    # int8 pools reject the legacy per-bucket prefill at engine init
    # (no quantize-on-append path) — those programs can never run, so
    # there is nothing to audit in the int8 arm
    return eng.cache_dtype == jnp.int8


def _one_bucket_avals(eng):
    # aval-only single-sequence bucket cache: eval_shape traces the
    # builder abstractly, so a production-size probe allocates nothing
    bucket = eng._buckets[0]
    return bucket, jax.eval_shape(
        lambda: eng.model.init_kv_caches(1, bucket,
                                         dtype=eng.cache_dtype))


def _probe_prefill_bucket(eng):
    if _legacy_prefill_blocked(eng):
        return _INT8_LEGACY_SKIP
    _lens, _bt, _caches, _samp, key = _probe_common(eng)
    bucket, one = _one_bucket_avals(eng)
    return Probe(eng._prefill(),
                 (eng._pb, jnp.zeros((1, bucket), jnp.int32), one,
                  bucket - 1, key, _samp_vectors(1), False),
                 (6,), ("pb", "ids", "caches", "last_idx", "key",
                        "samp"))


def _probe_prefill_insert(eng):
    if _legacy_prefill_blocked(eng):
        return _INT8_LEGACY_SKIP
    _bucket, one = _one_bucket_avals(eng)
    return Probe(eng._insert_contig(), (eng.caches, one, 0), (),
                 ("global_caches", "one_caches", "slot"))


def _probe_prefill_scatter(eng):
    if _legacy_prefill_blocked(eng):
        return _INT8_LEGACY_SKIP
    _bucket, one = _one_bucket_avals(eng)
    return Probe(eng._scatter_paged(),
                 (eng.layer_caches, one,
                  jnp.asarray(eng.pool.block_tables[0])),
                 (), ("layer_caches", "one_caches", "bt_row"))


def _probe_prefix_insert(eng):
    B = eng._prefix_block
    blk = jax.ShapeDtypeStruct(
        (eng._n_layers, B, eng._kvh, eng._hd),
        jnp.dtype(eng.cache_dtype))
    return Probe(eng._insert_prefix_contig(),
                 (eng.caches, blk, blk, 0, 0), (),
                 ("global_caches", "kblk", "vblk", "slot", "start"))


def _probe_prefix_read(eng):
    return Probe(eng._read_block_contig(), (eng.caches, 0, 0), (),
                 ("global_caches", "slot", "start"))


def _probe_page_copy(eng):
    return Probe(eng._copy_page(), (eng.layer_caches, 0, 1), (),
                 ("layer_caches", "src", "dst"))


_PROBES = {
    "decode_step": _probe_decode_step,
    "decode_chunk": _probe_decode_chunk,
    "spec_verify": _probe_spec_verify,
    "prefill_chunk": _probe_prefill_chunk,
    "prefill_bucket": _probe_prefill_bucket,
    "prefill_insert": _probe_prefill_insert,
    "prefill_scatter": _probe_prefill_scatter,
    "prefix_insert": _probe_prefix_insert,
    "prefix_read": _probe_prefix_read,
    "page_copy": _probe_page_copy,
}


# ---------------------------------------------------------------------------
# jaxpr analysis
# ---------------------------------------------------------------------------
# the narrow value-stream dtypes DQ monitors; index/bool arithmetic
# (int32 positions, bool masks) is not a value stream and stays out
_NARROW = {"bfloat16", "float16", "int8", "uint8", "int4", "uint4"}


def _dtype_name(d) -> str:
    try:
        return np.dtype(d).name
    except TypeError:
        return str(d)


def _monitored_widen(src: str, dst: str) -> bool:
    if src not in _NARROW:
        return False
    if src in ("bfloat16", "float16"):
        return dst in ("float32", "float64")
    # int8/int4: ANY float destination is a dequant-shaped widening —
    # bfloat16 included (it doesn't match "float*" by name, and
    # dequanting to the serving dtype is the most natural regression)
    return dst.startswith("float") or dst == "bfloat16"


def _is_literal(v) -> bool:
    return hasattr(v, "val")  # jaxpr Literals carry .val, Vars don't


def _walk(jxp, visit):
    """Depth-first over ``jxp``'s eqns and every sub-jaxpr hiding in
    eqn params — scan's single ClosedJaxpr, cond's TUPLE of branch
    jaxprs, custom-vjp bodies — so a callback or upcast cannot hide
    inside a branch."""

    def sub(v):
        if hasattr(v, "jaxpr"):              # ClosedJaxpr
            _walk(v.jaxpr, visit)
        elif hasattr(v, "eqns"):             # raw Jaxpr
            _walk(v, visit)
        elif isinstance(v, (tuple, list)):   # cond branches etc.
            for x in v:
                sub(x)

    for e in jxp.eqns:
        visit(e)
        for v in e.params.values():
            sub(v)


def _leaf_labels(args, static_argnums, argnames):
    """(root, label) per flattened dynamic-arg leaf, in invar order."""
    from jax import tree_util

    dyn = [a for i, a in enumerate(args) if i not in set(static_argnums)]
    if len(dyn) != len(argnames):
        raise AuditError(
            f"probe declares {len(argnames)} dynamic arg names but "
            f"{len(dyn)} dynamic args")
    out = []
    for name, a in zip(argnames, dyn):
        for path, _leaf in tree_util.tree_flatten_with_path(a)[0]:
            out.append((name, name + "".join(str(p) for p in path)))
    return out


def _allowed(label_pair, patterns) -> bool:
    root, label = label_pair
    return any(fnmatch.fnmatch(label, p) or root == p
               for p in patterns)


def audit_traced(program: str, fn, args, static_argnums, argnames,
                 contract: ProgramContract, *, arm: str = "engine",
                 baseline_entry: Optional[dict] = None,
                 check_size: bool = False):
    """Trace ``fn`` at ``args`` and audit the jaxpr against
    ``contract``. Returns ``(entry, violations)`` where ``entry`` is
    the report record (op counts, widenings, donation/dead views —
    ``eqns`` + ``widen`` are what the baseline pins). Trace-only: no
    compile, no dispatch, and the serving module's ``TRACE_COUNTS`` /
    ``TRACE_SHAPES`` are restored so compile accounting (watchdog,
    compile_counter guards) never sees the audit."""
    from ..inference import serving as S

    # restore is TARGETED, not a blanket snapshot rollback: tracing
    # ``program`` bumps exactly ITS key once — make_jaxpr opens its
    # own trace context, so the body re-runs even when the wrapper is
    # already warmed at these shapes (verified empirically on this
    # jax line; the audit-identity tests pin it) — so we subtract
    # only our own bump and restore only our own shape note. A
    # CONCURRENT engine's bump to any key (even the same one) during
    # the audit window survives the subtraction arithmetic, and its
    # recompile watchdog still sees what it must see
    before = S.TRACE_COUNTS.get(program, 0)
    shape_before = S.TRACE_SHAPES.get(program)
    had_shape = program in S.TRACE_SHAPES
    # abstract every array-shaped leaf down to its aval: the trace
    # needs only shapes/dtypes, and a seal-time audit on a production
    # engine must not transiently allocate anything (the legacy
    # bucket-cache probes would otherwise build real device buffers
    # at production shapes next to an HBM-full pool)
    args = tuple(
        jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
            if hasattr(x, "shape") and hasattr(x, "dtype") else x, a)
        if i not in set(static_argnums) else a
        for i, a in enumerate(args))
    ours = None
    try:
        closed = jax.make_jaxpr(
            fn, static_argnums=tuple(static_argnums))(*args)
        ours = S.TRACE_SHAPES.get(program)
    finally:
        if S.TRACE_COUNTS.get(program, 0) > before:
            S.TRACE_COUNTS[program] -= 1
            if S.TRACE_COUNTS[program] == 0:
                del S.TRACE_COUNTS[program]
        # shape-note restore is identity-guarded like the count
        # arithmetic: if a concurrent engine's recompile wrote a
        # FRESH note after our trace, that note must survive for its
        # watchdog artifact — we only roll back our own write
        if ours is not None and S.TRACE_SHAPES.get(program) \
                is not ours:
            pass
        elif had_shape:
            S.TRACE_SHAPES[program] = shape_before
        else:
            S.TRACE_SHAPES.pop(program, None)

    labels = _leaf_labels(args, static_argnums, argnames)
    eqns = closed.jaxpr.eqns
    if len(eqns) == 1 and eqns[0].primitive.name == "pjit" \
            and "jaxpr" in eqns[0].params:
        eq = eqns[0]
        inner = eq.params["jaxpr"].jaxpr
        donated_flags = eq.params.get(
            "donated_invars", (False,) * len(eq.invars))
        jitted = True
    else:
        inner = closed.jaxpr
        donated_flags = (False,) * len(inner.invars)
        jitted = False
    if len(inner.invars) != len(labels):
        raise AuditError(
            f"{arm}::{program}: traced {len(inner.invars)} invars but "
            f"probe flattens to {len(labels)} leaves — probe and "
            "program signature disagree")

    viol: List[AuditViolation] = []

    def v(rule, msg):
        viol.append(AuditViolation(arm, program, rule, msg))

    # ---- AL: donation both directions ----
    donated = {labels[i][0] for i, d in enumerate(donated_flags) if d}
    for name in contract.donate:
        idx = [i for i, (root, _l) in enumerate(labels)
               if root == name]
        if not idx:
            v("AL001", f"contract donates operand {name!r} but the "
                       "probe passes no such arg")
            continue
        missing = [labels[i][1] for i in idx if not donated_flags[i]]
        if missing:
            why = "" if jitted else " (program is not jit-wrapped — " \
                                    "nothing can alias)"
            v("AL001",
              f"pool operand {name!r} not donated: "
              f"{len(missing)}/{len(idx)} leaves un-aliased "
              f"(e.g. {missing[0]}){why} — every dispatch copies "
              "the pool instead of appending in place")
    for root in sorted(donated - set(contract.donate)):
        v("AL002",
          f"operand {root!r} is donated but the contract does not "
          "declare it — declare it (or stop donating): the contract "
          "must mirror the program")

    # ---- walk: op counts, widenings, callbacks ----
    n_eqns = 0
    widen: Counter = Counter()
    callbacks: List[str] = []

    def visit(e):
        nonlocal n_eqns
        n_eqns += 1
        name = e.primitive.name
        if name == "convert_element_type":
            src = _dtype_name(e.invars[0].aval.dtype)
            dst = _dtype_name(e.params["new_dtype"])
            if _monitored_widen(src, dst):
                widen[f"{src}->{dst}"] += 1
        elif name in ("dot_general", "conv_general_dilated"):
            # IMPLICIT widening: preferred_element_type lets a matmul
            # accumulate narrow operands straight into a wide output
            # with no convert eqn — the same stream-rewidening DQ
            # exists to catch, so it counts under the same pair
            order = ("int4", "uint4", "int8", "uint8", "float16",
                     "bfloat16")
            dst = _dtype_name(e.outvars[0].aval.dtype)
            srcs = sorted({_dtype_name(v.aval.dtype) for v in e.invars
                           if hasattr(v.aval, "dtype")
                           and _monitored_widen(
                               _dtype_name(v.aval.dtype), dst)},
                          key=order.index)
            if srcs:  # charge the narrowest operand's stream
                widen[f"{srcs[0]}->{dst}"] += 1
        if "callback" in name or name in ("infeed", "outfeed"):
            callbacks.append(name)

    _walk(inner, visit)

    # ---- TX ----
    for name in sorted(set(callbacks)):
        v("TX001",
          f"host callback/transfer primitive {name!r} "
          f"(x{callbacks.count(name)}) inside the program — serving "
          "dispatches must stay fully on-device/async")

    # ---- DQ ----
    for pair, count in sorted(widen.items()):
        if pair not in contract.widen_allow:
            v("DQ001",
              f"narrow stream widens {pair} x{count} with no "
              "contract allowance — a hidden upcast re-widens the "
              "bytes the perf models price as narrow (allowlist it "
              "in PROGRAM_CONTRACTS with a justification, or fix it)")
    if baseline_entry is not None:
        # exact pin, like SZ001: a count SHRINK left unpinned would be
        # silent headroom for a later upcast site to creep back into
        base_widen = baseline_entry.get("widen", {})
        for pair in sorted(set(widen) | set(base_widen)):
            count, base = int(widen.get(pair, 0)), \
                int(base_widen.get(pair, 0))
            if pair not in contract.widen_allow:
                # present-and-unallowlisted is DQ001's job; but a pin
                # whose pair vanished (site + allowance removed
                # together) must not ride the baseline forever
                if count == 0 and base > 0:
                    v("DQ002",
                      f"baseline pins widening {pair} x{base} but "
                      "the program no longer widens there — stale "
                      "pin, --write-baseline")
                continue
            if count != base:
                how = "grew" if count > base else "shrank"
                v("DQ002",
                  f"allowlisted widening {pair} {how} "
                  f"{base} -> {count} vs the baseline — review the "
                  "change and --write-baseline (a new upcast site "
                  "must not hide behind an existing allowance)")

    # ---- DD ----
    used = set()
    for e in inner.eqns:
        for var in e.invars:
            if not _is_literal(var):
                used.add(id(var))
    for var in inner.outvars:
        if not _is_literal(var):
            used.add(id(var))
    dead = [labels[i] for i, var in enumerate(inner.invars)
            if id(var) not in used]
    for pair in dead:
        if not _allowed(pair, contract.dead_ok):
            v("DD001",
              f"dead input {pair[1]!r}: the program never reads it "
              "but every dispatch flattens and ships it — drop it "
              "from the signature or allowlist it in dead_ok with "
              "a justification")
    # passthrough outputs are detected on the OUTER jaxpr: pjit
    # forwards a returned-unchanged input past the call boundary at
    # trace time, so the inner jaxpr no longer shows it
    outer = closed.jaxpr
    invar_ids = {id(var): labels[i][1]
                 for i, var in enumerate(outer.invars)}
    for j, var in enumerate(outer.outvars):
        if id(var) in invar_ids:
            lab = invar_ids[id(var)]
            if not _allowed((lab.split("[")[0].split(".")[0], lab),
                            contract.passthrough_ok):
                v("DD002",
                  f"output [{j}] passes input {lab!r} through "
                  "unchanged — it costs a donation slot / device "
                  "copy for nothing")
    # constant outputs: forward-propagate input dependence through
    # the inner eqns; an output no input reaches (a Literal, or a
    # value computed purely from trace constants) ships a dispatch
    # for something the host already knows
    dep = {id(var) for var in inner.invars}
    for e in inner.eqns:
        if any(not _is_literal(var) and id(var) in dep
               for var in e.invars):
            dep.update(id(o) for o in e.outvars)
    for j, var in enumerate(inner.outvars):
        if _is_literal(var) or id(var) not in dep:
            v("DD002",
              f"output [{j}] is a trace-time constant — compute it "
              "on the host instead of shipping a dispatch for it")
    dead_consts = [i for i, var in enumerate(inner.constvars)
                   if id(var) not in used]
    for i in dead_consts:
        v("DD003", f"trace constant [{i}] is captured but unused")

    # ---- SZ ----
    entry = {"eqns": n_eqns,
             "widen": {k: int(widen[k]) for k in sorted(widen)}}
    if check_size:
        if baseline_entry is None:
            v("SZ002",
              f"no baseline entry for {arm}::{program} — run "
              "--write-baseline and commit the diff")
        elif n_eqns != int(baseline_entry.get("eqns", -1)):
            base = int(baseline_entry.get("eqns", -1))
            how = "grew" if n_eqns > base else "shrank"
            v("SZ001",
              f"program op count {how} {base} -> {n_eqns} eqns vs "
              "the committed baseline — review the size change and "
              "--write-baseline")
    report = dict(entry)
    report["donated"] = sorted(donated)
    report["dead"] = sorted(lab for _r, lab in dead)
    return report, viol


# ---------------------------------------------------------------------------
# engine / repo auditors
# ---------------------------------------------------------------------------
def audit_engine(engine, arm: str = "engine",
                 baseline: Optional[Dict[str, dict]] = None) -> dict:
    """Audit every contracted program this engine can dispatch. SZ
    (op-count pinning) runs only when ``baseline`` entries are given —
    a production engine's op counts depend on its model, so size pins
    stay with the canonical tiny arms."""
    mode = "paged" if engine.cfg.paged else "contig"
    out = {"arm": arm, "programs": {}, "skipped": {}, "violations": []}
    for name in sorted(PROGRAM_CONTRACTS):
        contract = PROGRAM_CONTRACTS[name]
        if mode not in contract.modes:
            out["skipped"][name] = f"not a {mode}-mode program"
            continue
        builder = _PROBES.get(name)
        if builder is None:
            # PA001 forces a contract for every new program; nothing
            # static forces the probe — fail with the actionable
            # message, not a KeyError (the registry-completeness test
            # pins set(_PROBES) == set(PROGRAM_CONTRACTS) so this is
            # unreachable from the committed tree)
            raise AuditError(
                f"contracted program {name!r} has no probe — add a "
                "_PROBES entry in analysis/program_audit.py so the "
                "auditor can trace it")
        probe = builder(engine)
        if not isinstance(probe, Probe):
            # a probe may decline with a reason string (legacy path
            # blocked at init, chunked prefill disabled, ...): the
            # program cannot dispatch on THIS engine, so there is
            # nothing to audit — recorded, never silent
            out["skipped"][name] = probe or "probe declined"
            continue
        key = f"{arm}::{name}"
        entry, viol = audit_traced(
            name, probe.fn, probe.args, probe.static_argnums,
            probe.argnames, contract, arm=arm,
            baseline_entry=None if baseline is None
            else baseline.get(key),
            check_size=baseline is not None)
        out["programs"][name] = entry
        out["violations"].extend(viol)
    return out


def audit_repo(arms: Optional[Sequence[str]] = None,
               baseline: Optional[Dict[str, dict]] = None,
               use_baseline: bool = True) -> dict:
    """Audit the canonical tiny arms (the repo's real serving program
    set). Serving flags that shape the traces are pinned to their
    registry defaults for the duration and restored after, so the
    result is reproducible from any caller (CLI, bench, tests)."""
    arm_names = list(arms) if arms is not None else list(AUDIT_ARMS)
    bad = [a for a in arm_names if a not in AUDIT_ARMS]
    if bad:
        raise AuditError(
            f"unknown audit arm(s) {bad} (have {sorted(AUDIT_ARMS)})")
    if baseline is None and use_baseline:
        baseline = load_baseline(
            os.path.join(find_root(os.path.dirname(__file__)),
                         BASELINE_NAME))
    from ..core import random as _rng

    saved = {n: flags.flag(n) for n in _PINNED_FLAGS}
    flags.set_flags({n: flags.registry()[n]["default"]
                     for n in _PINNED_FLAGS})
    # tiny_model() seeds the global eager RNG stream; the audit must
    # not leak that side effect into the caller's run any more than
    # a flag flip (same save/restore contract)
    saved_state = (_rng._ensure_state().seed,
                   _rng._ensure_state().counter)
    try:
        model, _ = tiny_model()
        report = {"arms": {}, "entries": {}, "violations": []}
        for arm in arm_names:
            eng = build_audit_engine(arm, model=model)
            r = audit_engine(eng, arm=arm, baseline=baseline)
            report["arms"][arm] = r
            for name, entry in r["programs"].items():
                report["entries"][f"{arm}::{name}"] = {
                    "eqns": entry["eqns"], "widen": entry["widen"]}
            report["violations"].extend(r["violations"])
        return report
    finally:
        flags.set_flags(saved)
        st = _rng._ensure_state()
        st.seed, st.counter = saved_state


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------
def load_baseline(path: str) -> Dict[str, dict]:
    """Missing file = empty; a PRESENT but malformed file is a loud
    error, never a vacuously clean audit (ptlint's rule)."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        return {str(k): {"eqns": int(v["eqns"]),
                         "widen": {str(p): int(c)
                                   for p, c in v.get("widen",
                                                     {}).items()}}
                for k, v in data.get("entries", {}).items()}
    except OSError:
        return {}
    except (ValueError, TypeError, KeyError, AttributeError) as e:
        raise ValueError(
            f"invalid ptaudit baseline file {path}: {e} — fix it or "
            "regenerate with --write-baseline") from e


def write_baseline(path: str, entries: Dict[str, dict]):
    payload = {
        "comment": ("ptaudit per-program op-count / allowlisted-"
                    "widening pins, keyed arm::program; the CPU-"
                    "backend trace at the canonical tiny arms is "
                    "canonical. Regenerate with `python -m "
                    "paddle_tpu.analysis.audit --write-baseline` and "
                    "review the diff like any size change."),
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")


# ---------------------------------------------------------------------------
# CLI (python -m paddle_tpu.analysis.audit — see audit.py)
# ---------------------------------------------------------------------------
def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ptaudit",
        description="paddle_tpu jaxpr-level contract audit of the "
                    "compiled serving program set (aliasing, dtype "
                    "discipline, transfer bans, size budgets)")
    ap.add_argument("--arms", default=None,
                    help="comma-separated arm subset "
                         f"(default: {','.join(AUDIT_ARMS)})")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: <root>/"
                         f"{BASELINE_NAME})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip SZ/DQ002 baseline comparisons")
    ap.add_argument("--write-baseline", action="store_true",
                    help="pin the current op/widening counts")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--rules", action="store_true", dest="list_rules",
                    help="list rule ids and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, doc in sorted(RULE_DOCS.items()):
            print(f"{rid}  {doc}")
        return 0
    arm_names = [a.strip() for a in args.arms.split(",")] \
        if args.arms else None
    if arm_names:
        bad = [a for a in arm_names if a not in AUDIT_ARMS]
        if bad:
            print(f"ptaudit: unknown arm(s) {bad} "
                  f"(have {sorted(AUDIT_ARMS)})", file=sys.stderr)
            return 2
    root = find_root(os.path.dirname(os.path.abspath(__file__)))
    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    try:
        baseline = {} if (args.no_baseline or args.write_baseline) \
            else load_baseline(baseline_path)
    except ValueError as e:
        print(f"ptaudit: {e}", file=sys.stderr)
        return 2

    try:
        report = audit_repo(
            arms=arm_names,
            baseline=None if (args.no_baseline or args.write_baseline)
            else baseline,
            use_baseline=not (args.no_baseline
                              or args.write_baseline))
    except AuditError as e:
        # a broken probe/contract is a TOOLING error with an
        # actionable message, never a silent traceback or a clean exit
        print(f"ptaudit: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        # merge: a partial-arm run must not drop the OTHER arms' pins,
        # but within the arms just audited, stale pins (deleted or
        # renamed programs) are PRUNED — a dead entry nothing audits
        # would otherwise outlive its program and ambush a future
        # re-add with a years-stale SZ001 comparison. A corrupt
        # existing file must not kill the one command that can fix
        # it — warn and regenerate from this run's entries
        try:
            merged = load_baseline(baseline_path)
        except ValueError as e:
            print(f"ptaudit: replacing malformed baseline: {e}",
                  file=sys.stderr)
            merged = {}
        audited = tuple(f"{a}::" for a in report["arms"])
        merged = {k: v for k, v in merged.items()
                  if not k.startswith(audited)}
        merged.update(report["entries"])
        write_baseline(baseline_path, merged)
        print(f"ptaudit: wrote {len(report['entries'])} program "
              f"pin(s) to {baseline_path}")
        # the baseline can only accept SIZE/creep pins — structural
        # violations (AL/DQ001/TX/DD) the same audit found are not
        # waivable by re-pinning and must not ride out silently
        structural = report["violations"]
        if structural:
            for x in structural:
                print(f"{x.arm}::{x.program}: {x.rule} {x.message}")
            print(f"ptaudit: {len(structural)} structural "
                  "violation(s) remain — a baseline write cannot "
                  "accept these", file=sys.stderr)
            return 1
        return 0

    viol = report["violations"]
    if args.as_json:
        print(json.dumps({
            "arms": {a: {"programs": r["programs"],
                         "skipped": r["skipped"]}
                     for a, r in report["arms"].items()},
            "violations": [x.__dict__ for x in viol],
        }, indent=2))
        return 1 if viol else 0
    for x in viol:
        print(f"{x.arm}::{x.program}: {x.rule} {x.message}")
    n_prog = sum(len(r["programs"]) for r in report["arms"].values())
    n_skip = sum(len(r["skipped"]) for r in report["arms"].values())
    print(f"ptaudit: {len(report['arms'])} arm(s), {n_prog} "
          f"program(s) audited ({n_skip} skipped), {len(viol)} "
          "violation(s)")
    return 1 if viol else 0
