"""Static analysis + runtime invariant checking for the serving stack.

Two halves of one correctness story:

* :mod:`paddle_tpu.analysis.lint` — **ptlint**, an AST-based static
  lint (``python -m paddle_tpu.analysis.lint <paths>`` or the
  ``ptlint`` console entry) with rule families tuned to this codebase:
  trace-safety (TS), determinism (DT), flags hygiene (FL) and
  concurrency copy-on-read (CC). Catches the recompile hazards,
  host-sync leaks and scrape races *before* runtime that earlier PRs
  only caught by observation. The analysis engine is stdlib-``ast``
  only (importing :mod:`.lint`/:mod:`.rules` directly pulls in no
  jax; the ``-m``/console launches import the parent package once).

* :mod:`paddle_tpu.analysis.sanitizer` — a runtime invariant checker
  behind ``PT_FLAGS_sanitize`` (off = one identity check per hook
  site, the telemetry-off pattern): per-tick page/refcount
  conservation, slot-heap + block-table + scale-pool shape agreement,
  seq_len bounds, and a thread-ownership checker for scrape-thread
  reads. The chaos lane (``pytest -m chaos``) runs with it on.
"""

from .sanitizer import EngineSanitizer, SanitizerError  # noqa: F401
