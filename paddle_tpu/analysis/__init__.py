"""Static analysis + runtime invariant checking for the serving stack.

Three layers of one correctness story (source → trace → runtime):

* :mod:`paddle_tpu.analysis.lint` — **ptlint**, an AST-based static
  lint (``python -m paddle_tpu.analysis.lint <paths>`` or the
  ``ptlint`` console entry) with rule families tuned to this codebase:
  trace-safety (TS), determinism (DT), flags hygiene (FL) and
  concurrency copy-on-read (CC). Catches the recompile hazards,
  host-sync leaks and scrape races *before* runtime that earlier PRs
  only caught by observation. The analysis engine is stdlib-``ast``
  only (importing :mod:`.lint`/:mod:`.rules` directly pulls in no
  jax; the ``-m``/console launches import the parent package once).

* :mod:`paddle_tpu.analysis.program_audit` — **ptaudit**
  (``python -m paddle_tpu.analysis.audit``), a jaxpr-level contract
  auditor over the compiled serving program set: one declarative
  ``PROGRAM_CONTRACTS`` entry per ``TRACE_COUNTS`` program name
  (ptlint PA001 keeps the registry complete), traced at tiny
  CPU-friendly shapes and audited for donation/aliasing (AL), dtype
  discipline (DQ), host-transfer bans (TX), dead operands (DD) and
  op-count budgets against ``.ptaudit-baseline.json`` (SZ).
  ``PT_FLAGS_audit_on_seal`` lets production engines self-audit at
  ``seal_programs()``. ``python -m paddle_tpu.analysis.check`` runs
  ptlint + ptaudit as one gate with one exit code.

* :mod:`paddle_tpu.analysis.sanitizer` — a runtime invariant checker
  behind ``PT_FLAGS_sanitize`` (off = one identity check per hook
  site, the telemetry-off pattern): per-tick page/refcount
  conservation, slot-heap + block-table + scale-pool shape agreement,
  seq_len bounds, and a thread-ownership checker for scrape-thread
  reads. The chaos lane (``pytest -m chaos``) runs with it on.
"""

from .sanitizer import EngineSanitizer, SanitizerError  # noqa: F401
