"""``python -m paddle_tpu.analysis.audit`` — the ptaudit CLI.

Thin launcher for :mod:`paddle_tpu.analysis.program_audit` (the
contract registry, probes and rule families live there); mirrors
ptlint's UX: ``--json``, ``--rules``, ``--write-baseline``,
``--no-baseline``, ``--arms``, non-zero exit on violations. Unlike
ptlint this module is jax-heavy by nature — it traces the real
serving programs — so it is never imported by the lint path.
"""

from __future__ import annotations

import sys

from .program_audit import main  # noqa: F401

if __name__ == "__main__":
    sys.exit(main())
