"""ptlint — trace-safety / determinism / flags-hygiene / concurrency
static analysis for the paddle_tpu serving stack.

Usage::

    python -m paddle_tpu.analysis.lint paddle_tpu tests benchmarks
    ptlint paddle_tpu tests benchmarks          # console entry
    python -m paddle_tpu.analysis.lint --rules  # list rule families

Exit status: 0 when the scan is clean (after the committed baseline is
applied), 1 on any new violation, 2 on usage errors. The analysis
engine is pure stdlib ``ast`` — THIS module imports no jax and the
scan itself takes milliseconds; note the ``-m`` / console-entry
launches still import the parent ``paddle_tpu`` package (and thus
jax) once at startup.

**Suppressions** (use sparingly; ``paddle_tpu/inference`` and
``paddle_tpu/kernels`` are contractually suppression-free, enforced by
``tests/test_lint_clean.py``). Append a trailing comment of the form
``ptlint: disable=<RULE>`` (comma-separate several rule ids, e.g.
``disable=<RULEA>,<RULEB>``) to the flagged line; a whole module opts
out with ``ptlint: skip-file`` in its first 5 lines.

**Baseline**: ``.ptlint-baseline.json`` at the repo root records
accepted pre-existing violations as ``{"file::RULE": count}``; the
linter only fails on violations beyond it (diff-friendly: counts, not
line numbers). Regenerate with ``--write-baseline`` — but prefer
fixing the finding; the committed baseline is empty.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .rules import ALL_RULES, RULE_DOCS, Project, Violation

BASELINE_NAME = ".ptlint-baseline.json"
_SUPPRESS_RE = re.compile(r"#\s*ptlint:\s*disable=([A-Z]{2}\d{3}"
                          r"(?:\s*,\s*[A-Z]{2}\d{3})*)")
_SKIP_FILE_RE = re.compile(r"#\s*ptlint:\s*skip-file")


@dataclass
class Suppression:
    file: str
    line: int
    rules: Tuple[str, ...]  # () == skip-file


@dataclass
class ScanResult:
    violations: List[Violation]
    suppressions: List[Suppression]
    suppressed: List[Violation]
    files: int


def find_root(start: str) -> str:
    """Nearest ancestor carrying pyproject.toml (fallback: start)."""
    cur = os.path.abspath(start)
    while True:
        if os.path.exists(os.path.join(cur, "pyproject.toml")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start)
        cur = parent


def iter_py_files(paths: Sequence[str]):
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(
                d for d in dirnames
                if not d.startswith(".") and d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _parse_suppressions(src: str, relpath: str) -> List[Suppression]:
    out: List[Suppression] = []
    lines = src.splitlines()
    for i, line in enumerate(lines[:5], start=1):
        if _SKIP_FILE_RE.search(line):
            return [Suppression(relpath, i, ())]
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            rules = tuple(r.strip() for r in m.group(1).split(","))
            out.append(Suppression(relpath, i, rules))
    return out


def scan(paths: Sequence[str], root: Optional[str] = None) -> ScanResult:
    """Run every rule over ``paths``; returns violations with
    suppressions already applied (they land in ``suppressed``)."""
    root = root or find_root(paths[0] if paths else ".")
    project = Project(root)
    violations: List[Violation] = []
    suppressions: List[Suppression] = []
    suppressed: List[Violation] = []
    n_files = 0
    for path in iter_py_files(paths):
        relpath = os.path.relpath(os.path.abspath(path), root) \
            .replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
        except (OSError, SyntaxError) as e:
            violations.append(Violation(
                relpath, getattr(e, "lineno", 1) or 1, "XX001",
                f"unparseable module: {e}"))
            continue
        n_files += 1
        sups = _parse_suppressions(src, relpath)
        suppressions.extend(sups)
        skip_all = any(s.rules == () for s in sups)
        per_line: Dict[int, Tuple[str, ...]] = {
            s.line: s.rules for s in sups if s.rules}
        for rule in ALL_RULES:
            if not rule.applies(relpath):
                continue
            for v in rule.check_module(project, tree, src, relpath):
                v.file = v.file or relpath
                if skip_all or v.rule in per_line.get(v.line, ()):
                    suppressed.append(v)
                else:
                    violations.append(v)
    for rule in ALL_RULES:
        for v in rule.check_project(project):
            # project-level findings anchor to real files too;
            # line-level suppressions apply the same way
            sup = next(
                (s for s in suppressions
                 if s.file == v.file
                 and (s.rules == () or
                      (s.line == v.line and v.rule in s.rules))),
                None)
            (suppressed if sup else violations).append(v)
    # dedup: taint analysis walks loop bodies twice (loop-carried
    # state), which can report one site twice
    seen = set()
    unique = []
    for v in sorted(violations,
                    key=lambda v: (v.file, v.line, v.rule)):
        k = (v.file, v.line, v.rule, v.message)
        if k not in seen:
            seen.add(k)
            unique.append(v)
    return ScanResult(unique, suppressions, suppressed, n_files)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------
def load_baseline(path: str) -> Dict[str, int]:
    """Missing file = empty baseline; a PRESENT but malformed file is
    a loud, clearly-attributed error (a merge-conflict marker in the
    baseline must not read as a lint crash — or worse, pass)."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        return {str(k): int(v)
                for k, v in data.get("entries", {}).items()}
    except OSError:
        return {}
    except (ValueError, TypeError, AttributeError) as e:
        raise ValueError(
            f"invalid ptlint baseline file {path}: {e} — fix it or "
            "regenerate with --write-baseline") from e


def apply_baseline(violations: List[Violation],
                   baseline: Dict[str, int]
                   ) -> Tuple[List[Violation], List[Violation]]:
    """(new, accepted): per (file, rule) pair the first ``count``
    violations are accepted, the rest are new."""
    budget = dict(baseline)
    new: List[Violation] = []
    accepted: List[Violation] = []
    for v in violations:
        if budget.get(v.key(), 0) > 0:
            budget[v.key()] -= 1
            accepted.append(v)
        else:
            new.append(v)
    return new, accepted


def write_baseline(path: str, violations: List[Violation]):
    entries: Dict[str, int] = {}
    for v in violations:
        entries[v.key()] = entries.get(v.key(), 0) + 1
    payload = {
        "comment": ("ptlint accepted pre-existing violations; "
                    "entries under paddle_tpu/inference/ and "
                    "paddle_tpu/kernels/ are FORBIDDEN "
                    "(tests/test_lint_clean.py)"),
        "entries": dict(sorted(entries.items())),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ptlint",
        description="paddle_tpu static analysis: trace-safety, "
                    "determinism, flags hygiene, concurrency")
    ap.add_argument("paths", nargs="*", help="files or directories")
    ap.add_argument("--root", default=None,
                    help="repo root (default: nearest pyproject.toml)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: <root>/{BASELINE_NAME}"
                         " when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every violation, baseline ignored")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept current violations into the baseline")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--rules", action="store_true", dest="list_rules",
                    help="list rule ids and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, doc in sorted(RULE_DOCS.items()):
            print(f"{rid}  {doc}")
        return 0
    if not args.paths:
        ap.print_usage(sys.stderr)
        return 2
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        # a typo'd path must not read as a vacuously clean scan
        print(f"ptlint: no such file or directory: {missing}",
              file=sys.stderr)
        return 2

    root = args.root or find_root(args.paths[0])
    result = scan(args.paths, root)
    if result.files == 0:
        # existing-but-python-free paths must not read as a
        # vacuously clean scan either
        print("ptlint: no Python files found under "
              f"{list(args.paths)}", file=sys.stderr)
        return 2
    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    try:
        baseline = {} if args.no_baseline \
            else load_baseline(baseline_path)
    except ValueError as e:
        print(f"ptlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        write_baseline(baseline_path, result.violations)
        print(f"ptlint: wrote {len(result.violations)} accepted "
              f"violation(s) to {baseline_path}")
        return 0

    new, accepted = apply_baseline(result.violations, baseline)

    if args.as_json:
        print(json.dumps({
            "files": result.files,
            "violations": [v.__dict__ for v in new],
            "baselined": [v.__dict__ for v in accepted],
            "suppressions": [s.__dict__ for s in result.suppressions],
        }, indent=2, default=list))
        return 1 if new else 0

    for v in new:
        print(f"{v.file}:{v.line}: {v.rule} {v.message}")
    n_sup = len(result.suppressions)
    tail = []
    if accepted:
        tail.append(f"{len(accepted)} baselined")
    if n_sup:
        tail.append(f"{n_sup} suppression(s)")
    extra = f" ({', '.join(tail)})" if tail else ""
    print(f"ptlint: {result.files} file(s), {len(new)} "
          f"violation(s){extra}")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
