"""Tensor creation & math API (parity: python/paddle/tensor/).

On TPU the tensor type IS ``jax.Array``; this module provides the
paddle-flavored creation/math surface over jax.numpy. No wrapper class: a
wrapper would break jax transforms and buy nothing — XLA is the dispatch
layer that paddle's pybind/phi stack (paddle/fluid/pybind/,
paddle/phi/api/) hand-builds on GPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .core import dtype as dtype_mod
from .core.parameter import Parameter


def _v(x):
    return x.value if isinstance(x, Parameter) else x


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    dt = dtype_mod.convert_dtype(dtype) if dtype is not None else None
    return jnp.asarray(_v(data), dtype=dt)


def zeros(shape, dtype=None):
    return jnp.zeros(shape, dtype_mod.convert_dtype(dtype))


def ones(shape, dtype=None):
    return jnp.ones(shape, dtype_mod.convert_dtype(dtype))


def full(shape, fill_value, dtype=None):
    return jnp.full(shape, fill_value, dtype_mod.convert_dtype(dtype))


def zeros_like(x, dtype=None):
    return jnp.zeros_like(_v(x), dtype=dtype and dtype_mod.convert_dtype(dtype))


def ones_like(x, dtype=None):
    return jnp.ones_like(_v(x), dtype=dtype and dtype_mod.convert_dtype(dtype))


def full_like(x, fill_value, dtype=None):
    return jnp.full_like(
        _v(x), fill_value, dtype=dtype and dtype_mod.convert_dtype(dtype)
    )


def arange(start, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    return jnp.arange(start, end, step, dtype and dtype_mod.convert_dtype(dtype))


def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, num, dtype=dtype and dtype_mod.convert_dtype(dtype))


def eye(n, m=None, dtype=None):
    return jnp.eye(n, m, dtype=dtype_mod.convert_dtype(dtype))


def empty(shape, dtype=None):
    return jnp.zeros(shape, dtype_mod.convert_dtype(dtype))


# math — re-export the jnp surface with paddle names
def _alias(fn):
    def wrapped(*args, **kwargs):
        args = tuple(_v(a) for a in args)
        return fn(*args, **kwargs)

    wrapped.__name__ = fn.__name__
    return wrapped


matmul = _alias(jnp.matmul)
add = _alias(jnp.add)
subtract = _alias(jnp.subtract)
multiply = _alias(jnp.multiply)
divide = _alias(jnp.divide)
pow = _alias(jnp.power)  # noqa: A001
sqrt = _alias(jnp.sqrt)
rsqrt = _alias(jax.lax.rsqrt)
exp = _alias(jnp.exp)
log = _alias(jnp.log)
abs = _alias(jnp.abs)  # noqa: A001
mean = _alias(jnp.mean)
sum = _alias(jnp.sum)  # noqa: A001
max = _alias(jnp.max)  # noqa: A001
min = _alias(jnp.min)  # noqa: A001
argmax = _alias(jnp.argmax)
argmin = _alias(jnp.argmin)
maximum = _alias(jnp.maximum)
minimum = _alias(jnp.minimum)
clip = _alias(jnp.clip)
reshape = _alias(jnp.reshape)
transpose = _alias(jnp.transpose)
squeeze = _alias(jnp.squeeze)
unsqueeze = _alias(jnp.expand_dims)
concat = _alias(jnp.concatenate)
stack = _alias(jnp.stack)
split = _alias(jnp.split)
where = _alias(jnp.where)
cast = _alias(lambda x, dtype: x.astype(dtype_mod.convert_dtype(dtype)))
tanh = _alias(jnp.tanh)
sin = _alias(jnp.sin)
cos = _alias(jnp.cos)
floor = _alias(jnp.floor)
ceil = _alias(jnp.ceil)
round = _alias(jnp.round)  # noqa: A001
sign = _alias(jnp.sign)
cumsum = _alias(jnp.cumsum)
cumprod = _alias(jnp.cumprod)
sort = _alias(jnp.sort)
argsort = _alias(jnp.argsort)
gather = _alias(lambda x, index, axis=0: jnp.take(x, index, axis=axis))
einsum = _alias(jnp.einsum)
tril = _alias(jnp.tril)
triu = _alias(jnp.triu)


def topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    """Paddle semantics: (values, indices) along ``axis``; ``largest``
    selects direction (jax.lax.top_k is last-axis/largest-only)."""
    x = _v(x)
    moved = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, idx = jax.lax.top_k(moved, k)
    else:
        vals, idx = jax.lax.top_k(-moved, k)
        vals = -vals
    return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis)


def flatten(x, start_axis=0, stop_axis=-1):
    """Paddle semantics: collapse axes [start_axis, stop_axis] into one
    (paddle.flatten(x, 1) is the canonical NCHW→NC call)."""
    x = _v(x)
    nd = x.ndim
    if nd == 0:
        return jnp.reshape(x, (1,))
    s = start_axis + nd if start_axis < 0 else start_axis
    e = stop_axis + nd if stop_axis < 0 else stop_axis
    if not (0 <= s <= e < nd):
        raise ValueError(
            f"flatten: invalid range start_axis={start_axis} "
            f"stop_axis={stop_axis} for ndim={nd}")
    new_shape = x.shape[:s] + (-1,) + x.shape[e + 1:]
    return jnp.reshape(x, new_shape)


def gather_nd(x, index):
    """index[..., :k] indexes the first k dims of x (paddle.gather_nd)."""
    x, index = _v(x), _v(index)
    k = index.shape[-1]
    idx = tuple(index[..., i] for i in range(k))
    return x[idx]


def scatter(x, index, updates, overwrite=True):
    """paddle.scatter: write ``updates`` rows into x at 1-D ``index``."""
    x, index, updates = _v(x), _v(index), _v(updates)
    if overwrite:
        return x.at[index].set(updates)
    # paddle's overwrite=False accumulates (after zeroing target rows)
    zeroed = x.at[index].set(0)
    return zeroed.at[index].add(updates)


def scatter_nd_add(x, index, updates):
    x, index, updates = _v(x), _v(index), _v(updates)
    k = index.shape[-1]
    idx = tuple(index[..., i] for i in range(k))
    return x.at[idx].add(updates)


def put_along_axis(x, indices, values, axis):
    x = _v(x)
    return x.at[
        tuple(
            _v(indices) if i == (axis % x.ndim) else
            jnp.arange(x.shape[i]).reshape(
                [-1 if j == i else 1 for j in range(x.ndim)])
            for i in range(x.ndim)
        )
    ].set(_v(values))
isnan = _alias(jnp.isnan)
isinf = _alias(jnp.isinf)
isfinite = _alias(jnp.isfinite)
equal = _alias(jnp.equal)
not_equal = _alias(jnp.not_equal)
greater_than = _alias(jnp.greater)
less_than = _alias(jnp.less)
logical_and = _alias(jnp.logical_and)
logical_or = _alias(jnp.logical_or)
logical_not = _alias(jnp.logical_not)
all = _alias(jnp.all)  # noqa: A001
any = _alias(jnp.any)  # noqa: A001
square = _alias(jnp.square)
log_softmax = _alias(jax.nn.log_softmax)
softmax = _alias(jax.nn.softmax)
var = _alias(jnp.var)
std = _alias(jnp.std)


def norm(x, p="fro", axis=None, keepdim=False):
    """Paddle semantics: axis=None flattens (any rank) and computes a
    vector norm; 'fro'≡p=2 elementwise. int axis → vector p-norm;
    2-tuple axis → matrix norm (jnp.linalg.norm rejects ndim>2 with
    axis=None, and its defaults differ — hence no alias)."""
    x = _v(x)
    if axis is None:
        flat = jnp.ravel(x)
        pp = 2.0 if p in ("fro", None) else p
        if pp == float("inf"):
            return jnp.max(jnp.abs(flat))
        if pp == float("-inf"):
            return jnp.min(jnp.abs(flat))
        out = jnp.sum(jnp.abs(flat) ** pp) ** (1.0 / pp)
        return jnp.reshape(out, (1,) * x.ndim) if keepdim else out
    if isinstance(axis, (tuple, list)):
        ord_ = "fro" if p in ("fro", None) else p
        return jnp.linalg.norm(x, ord=ord_, axis=tuple(axis),
                               keepdims=keepdim)
    pp = 2.0 if p in ("fro", None) else p
    if pp == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if pp == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** pp, axis=axis, keepdims=keepdim) ** (1.0 / pp)
dot = _alias(jnp.dot)
outer = _alias(jnp.outer)
roll = _alias(jnp.roll)
flip = _alias(jnp.flip)
tile = _alias(jnp.tile)
repeat_interleave = _alias(jnp.repeat)
broadcast_to = _alias(jnp.broadcast_to)
expand = _alias(jnp.broadcast_to)
take_along_axis = _alias(jnp.take_along_axis)
index_select = _alias(lambda x, index, axis=0: jnp.take(x, index, axis=axis))
masked_select = _alias(lambda x, mask: x[mask])
numel = _alias(jnp.size)
diag = _alias(jnp.diag)
