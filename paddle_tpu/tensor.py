"""Tensor creation & math API (parity: python/paddle/tensor/).

On TPU the tensor type IS ``jax.Array``; this module provides the
paddle-flavored creation/math surface over jax.numpy. No wrapper class: a
wrapper would break jax transforms and buy nothing — XLA is the dispatch
layer that paddle's pybind/phi stack (paddle/fluid/pybind/,
paddle/phi/api/) hand-builds on GPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .core import dtype as dtype_mod
from .core.parameter import Parameter


def _v(x):
    return x.value if isinstance(x, Parameter) else x


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    dt = dtype_mod.convert_dtype(dtype) if dtype is not None else None
    return jnp.asarray(_v(data), dtype=dt)


def zeros(shape, dtype=None):
    return jnp.zeros(shape, dtype_mod.convert_dtype(dtype))


def ones(shape, dtype=None):
    return jnp.ones(shape, dtype_mod.convert_dtype(dtype))


def full(shape, fill_value, dtype=None):
    return jnp.full(shape, fill_value, dtype_mod.convert_dtype(dtype))


def zeros_like(x, dtype=None):
    return jnp.zeros_like(_v(x), dtype=dtype and dtype_mod.convert_dtype(dtype))


def ones_like(x, dtype=None):
    return jnp.ones_like(_v(x), dtype=dtype and dtype_mod.convert_dtype(dtype))


def full_like(x, fill_value, dtype=None):
    return jnp.full_like(
        _v(x), fill_value, dtype=dtype and dtype_mod.convert_dtype(dtype)
    )


def arange(start, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    return jnp.arange(start, end, step, dtype and dtype_mod.convert_dtype(dtype))


def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, num, dtype=dtype and dtype_mod.convert_dtype(dtype))


def eye(n, m=None, dtype=None):
    return jnp.eye(n, m, dtype=dtype_mod.convert_dtype(dtype))


def empty(shape, dtype=None):
    return jnp.zeros(shape, dtype_mod.convert_dtype(dtype))


# math — re-export the jnp surface with paddle names
def _alias(fn):
    def wrapped(*args, **kwargs):
        args = tuple(_v(a) for a in args)
        return fn(*args, **kwargs)

    wrapped.__name__ = fn.__name__
    return wrapped


matmul = _alias(jnp.matmul)
add = _alias(jnp.add)
subtract = _alias(jnp.subtract)
multiply = _alias(jnp.multiply)
divide = _alias(jnp.divide)
pow = _alias(jnp.power)  # noqa: A001
sqrt = _alias(jnp.sqrt)
rsqrt = _alias(jax.lax.rsqrt)
exp = _alias(jnp.exp)
log = _alias(jnp.log)
abs = _alias(jnp.abs)  # noqa: A001
mean = _alias(jnp.mean)
sum = _alias(jnp.sum)  # noqa: A001
max = _alias(jnp.max)  # noqa: A001
min = _alias(jnp.min)  # noqa: A001
argmax = _alias(jnp.argmax)
argmin = _alias(jnp.argmin)
maximum = _alias(jnp.maximum)
minimum = _alias(jnp.minimum)
clip = _alias(jnp.clip)
reshape = _alias(jnp.reshape)
transpose = _alias(jnp.transpose)
squeeze = _alias(jnp.squeeze)
unsqueeze = _alias(jnp.expand_dims)
concat = _alias(jnp.concatenate)
stack = _alias(jnp.stack)
split = _alias(jnp.split)
where = _alias(jnp.where)
cast = _alias(lambda x, dtype: x.astype(dtype_mod.convert_dtype(dtype)))
tanh = _alias(jnp.tanh)
sin = _alias(jnp.sin)
cos = _alias(jnp.cos)
floor = _alias(jnp.floor)
ceil = _alias(jnp.ceil)
round = _alias(jnp.round)  # noqa: A001
sign = _alias(jnp.sign)
cumsum = _alias(jnp.cumsum)
cumprod = _alias(jnp.cumprod)
sort = _alias(jnp.sort)
argsort = _alias(jnp.argsort)
gather = _alias(lambda x, index, axis=0: jnp.take(x, index, axis=axis))
einsum = _alias(jnp.einsum)
tril = _alias(jnp.tril)
triu = _alias(jnp.triu)


def topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    """Paddle semantics: (values, indices) along ``axis``; ``largest``
    selects direction (jax.lax.top_k is last-axis/largest-only)."""
    x = _v(x)
    moved = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, idx = jax.lax.top_k(moved, k)
    else:
        vals, idx = jax.lax.top_k(-moved, k)
        vals = -vals
    return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis)


def flatten(x, start_axis=0, stop_axis=-1):
    """Paddle semantics: collapse axes [start_axis, stop_axis] into one
    (paddle.flatten(x, 1) is the canonical NCHW→NC call)."""
    x = _v(x)
    nd = x.ndim
    if nd == 0:
        return jnp.reshape(x, (1,))
    s = start_axis + nd if start_axis < 0 else start_axis
    e = stop_axis + nd if stop_axis < 0 else stop_axis
    if not (0 <= s <= e < nd):
        raise ValueError(
            f"flatten: invalid range start_axis={start_axis} "
            f"stop_axis={stop_axis} for ndim={nd}")
    new_shape = x.shape[:s] + (-1,) + x.shape[e + 1:]
    return jnp.reshape(x, new_shape)


def gather_nd(x, index):
    """index[..., :k] indexes the first k dims of x (paddle.gather_nd)."""
    x, index = _v(x), _v(index)
    k = index.shape[-1]
    idx = tuple(index[..., i] for i in range(k))
    return x[idx]


def scatter(x, index, updates, overwrite=True):
    """paddle.scatter: write ``updates`` rows into x at 1-D ``index``."""
    x, index, updates = _v(x), _v(index), _v(updates)
    if overwrite:
        return x.at[index].set(updates)
    # paddle's overwrite=False accumulates (after zeroing target rows)
    zeroed = x.at[index].set(0)
    return zeroed.at[index].add(updates)


def scatter_nd_add(x, index, updates):
    x, index, updates = _v(x), _v(index), _v(updates)
    k = index.shape[-1]
    idx = tuple(index[..., i] for i in range(k))
    return x.at[idx].add(updates)


def put_along_axis(x, indices, values, axis):
    x = _v(x)
    return x.at[
        tuple(
            _v(indices) if i == (axis % x.ndim) else
            jnp.arange(x.shape[i]).reshape(
                [-1 if j == i else 1 for j in range(x.ndim)])
            for i in range(x.ndim)
        )
    ].set(_v(values))
isnan = _alias(jnp.isnan)
isinf = _alias(jnp.isinf)
isfinite = _alias(jnp.isfinite)
equal = _alias(jnp.equal)
not_equal = _alias(jnp.not_equal)
greater_than = _alias(jnp.greater)
less_than = _alias(jnp.less)
logical_and = _alias(jnp.logical_and)
logical_or = _alias(jnp.logical_or)
logical_not = _alias(jnp.logical_not)
all = _alias(jnp.all)  # noqa: A001
any = _alias(jnp.any)  # noqa: A001
square = _alias(jnp.square)
log_softmax = _alias(jax.nn.log_softmax)
softmax = _alias(jax.nn.softmax)
var = _alias(jnp.var)
std = _alias(jnp.std)


def norm(x, p="fro", axis=None, keepdim=False):
    """Paddle semantics: axis=None flattens (any rank) and computes a
    vector norm; 'fro'≡p=2 elementwise. int axis → vector p-norm;
    2-tuple axis → matrix norm (jnp.linalg.norm rejects ndim>2 with
    axis=None, and its defaults differ — hence no alias)."""
    x = _v(x)
    if axis is None:
        flat = jnp.ravel(x)
        pp = 2.0 if p in ("fro", None) else p
        if pp == float("inf"):
            return jnp.max(jnp.abs(flat))
        if pp == float("-inf"):
            return jnp.min(jnp.abs(flat))
        out = jnp.sum(jnp.abs(flat) ** pp) ** (1.0 / pp)
        return jnp.reshape(out, (1,) * x.ndim) if keepdim else out
    if isinstance(axis, (tuple, list)):
        ord_ = "fro" if p in ("fro", None) else p
        return jnp.linalg.norm(x, ord=ord_, axis=tuple(axis),
                               keepdims=keepdim)
    pp = 2.0 if p in ("fro", None) else p
    if pp == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if pp == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** pp, axis=axis, keepdims=keepdim) ** (1.0 / pp)
def dot(x, y, name=None):
    """Parity: paddle.dot — 1-D inner product; 2-D is the PER-ROW inner
    product returning [batch] (NOT a matmul, unlike numpy/jax dot)."""
    x, y = _v(x), _v(y)
    if x.ndim == 1:
        return jnp.sum(x * y)
    if x.ndim == 2:
        return jnp.sum(x * y, axis=-1)
    raise ValueError(f"dot expects 1-D/2-D inputs, got {x.ndim}-D")


outer = _alias(jnp.outer)
roll = _alias(jnp.roll)
flip = _alias(jnp.flip)
tile = _alias(jnp.tile)
repeat_interleave = _alias(jnp.repeat)
broadcast_to = _alias(jnp.broadcast_to)
expand = _alias(jnp.broadcast_to)
take_along_axis = _alias(jnp.take_along_axis)
index_select = _alias(lambda x, index, axis=0: jnp.take(x, index, axis=axis))
masked_select = _alias(lambda x, mask: x[mask])
numel = _alias(jnp.size)
diag = _alias(jnp.diag)


# ---------------------------------------------------------------------------
# round-3 widening of the paddle tensor surface
# (parity: python/paddle/tensor/{math,manipulation,search,stat}.py)
# ---------------------------------------------------------------------------
bincount = _alias(jnp.bincount)
kron = _alias(jnp.kron)
trace = _alias(jnp.trace)
diagonal = _alias(jnp.diagonal)
meshgrid = _alias(jnp.meshgrid)
logsumexp = _alias(jax.scipy.special.logsumexp)
nanmean = _alias(jnp.nanmean)
nansum = _alias(jnp.nansum)
amax = _alias(jnp.max)
amin = _alias(jnp.min)
diff = _alias(jnp.diff)
searchsorted = _alias(
    lambda sorted_sequence, values, right=False: jnp.searchsorted(
        sorted_sequence, values, side="right" if right else "left"))
bucketize = _alias(
    lambda x, sorted_sequence, right=False: jnp.searchsorted(
        sorted_sequence, x, side="right" if right else "left"))
histogram = _alias(
    lambda x, bins=100, min=0, max=0: jnp.histogram(  # noqa: A002
        x, bins=bins,
        range=None if (min == 0 and max == 0) else (min, max))[0])
lerp = _alias(lambda x, y, weight: x + weight * (y - x))
addmm = _alias(
    lambda input, x, y, beta=1.0, alpha=1.0: beta * input  # noqa: A002
    + alpha * (x @ y))
logaddexp = _alias(jnp.logaddexp)
heaviside = _alias(jnp.heaviside)
rad2deg = _alias(jnp.rad2deg)
deg2rad = _alias(jnp.deg2rad)
frac = _alias(lambda x: x - jnp.trunc(x))
trunc = _alias(jnp.trunc)
expm1 = _alias(jnp.expm1)
log1p = _alias(jnp.log1p)
log2 = _alias(jnp.log2)
log10 = _alias(jnp.log10)
atan2 = _alias(jnp.arctan2)
hypot = _alias(jnp.hypot)
copysign = _alias(jnp.copysign)
nextafter = _alias(jnp.nextafter)
gcd = _alias(jnp.gcd)
lcm = _alias(jnp.lcm)
isclose = _alias(jnp.isclose)
allclose = _alias(jnp.allclose)
inner = _alias(jnp.inner)
cross = _alias(jnp.cross)
clone = _alias(jnp.copy)
rot90 = _alias(jnp.rot90)
vander = _alias(lambda x, n=None, increasing=False: jnp.vander(
    x, N=n, increasing=increasing))


def nonzero(x, as_tuple=False):
    """Paddle semantics: one [N, ndim] int64 tensor of coordinates
    (jnp.nonzero's tuple-of-arrays only with as_tuple=True). Dynamic
    output size — eager-only, like the reference's CPU path."""
    res = jnp.nonzero(_v(x))
    if as_tuple:
        return res
    return jnp.stack(res, axis=-1)


def median(x, axis=None, keepdim=False):
    return jnp.median(_v(x), axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(_v(x), jnp.asarray(q), axis=axis, keepdims=keepdim)


def mode(x, axis=-1, keepdim=False):
    """Paddle semantics: (values, indices) of the most frequent element
    along ``axis``. Static-shape formulation: each position's count is
    how many elements along the axis equal it; argmax of counts over the
    SORTED axis picks the modal value (ties → a smallest-value run)."""
    x = _v(x)
    if axis % x.ndim != x.ndim - 1:
        moved = jnp.moveaxis(x, axis, -1)
        values, idx = mode(moved, axis=-1)
        if keepdim:
            values = jnp.expand_dims(values, axis)
            idx = jnp.expand_dims(idx, axis)
        return values, idx
    sorted_x = jnp.sort(x, axis=-1)
    counts = jnp.sum(
        (sorted_x[..., :, None] == sorted_x[..., None, :]), axis=-1)
    best = jnp.argmax(counts, axis=-1)
    values = jnp.take_along_axis(sorted_x, best[..., None], axis=-1)[..., 0]
    # index of an occurrence of the modal value in the ORIGINAL order
    idx = jnp.argmax(x == values[..., None], axis=-1)
    if keepdim:
        values = values[..., None]
        idx = idx[..., None]
    return values, idx


def unique(x, return_index=False, return_inverse=False,
           return_counts=False, axis=None):
    """jnp.unique under jit needs static sizes; eager paddle semantics
    here (host-side op, like the reference's CPU fallback)."""
    import numpy as np

    res = np.unique(np.asarray(_v(x)), return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(jnp.asarray(r) for r in res)
    return jnp.asarray(res)


def unbind(x, axis=0):
    x = _v(x)
    return [jnp.squeeze(s, axis) for s in
            jnp.split(x, x.shape[axis], axis)]


def chunk(x, chunks, axis=0):
    return jnp.array_split(_v(x), chunks, axis)


def masked_fill(x, mask, value):
    return jnp.where(_v(mask), value, _v(x))


def logcumsumexp(x, axis=None):
    x = _v(x)
    if axis is None:
        x = x.ravel()
        axis = 0
    m = jnp.max(x, axis=axis, keepdims=True)
    return m + jnp.log(jnp.cumsum(jnp.exp(x - m), axis=axis))


def tensordot(x, y, axes=2):
    return jnp.tensordot(_v(x), _v(y), axes=axes)


def renorm(x, p, axis, max_norm):
    """Parity: paddle.renorm — rescale each sub-tensor along ``axis`` so
    its p-norm is at most max_norm."""
    x = _v(x)
    axis = axis % x.ndim
    other = tuple(i for i in range(x.ndim) if i != axis)
    norms = jnp.sum(jnp.abs(x) ** p, axis=other, keepdims=True) ** (1.0 / p)
    scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * scale


def scatter_nd(index, updates, shape):
    """Parity: paddle.scatter_nd — zeros of ``shape`` with ``updates``
    scatter-ADDED at ``index`` (duplicates accumulate)."""
    index = _v(index)
    updates = _v(updates)
    out = jnp.zeros(tuple(shape), updates.dtype)
    idx_tuple = tuple(jnp.moveaxis(index, -1, 0))
    return out.at[idx_tuple].add(updates)


def scatter_nd_add(x, index, updates):
    x = _v(x)
    index = _v(index)
    idx_tuple = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx_tuple].add(_v(updates))


def rand(shape, dtype=None):
    """Parity: paddle.rand — U[0,1) from the global seed stream
    (delegates to core.random so dtype strings resolve uniformly)."""
    from .core import random as _r

    return _r.uniform(tuple(shape), dtype, 0.0, 1.0)


def randn(shape, dtype=None):
    """Parity: paddle.randn."""
    from .core import random as _r

    return _r.normal(tuple(shape), dtype)


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    """Parity: paddle.randint."""
    from .core import random as _r

    return _r.randint(low, high, tuple(shape), dtype)


def randperm(n, dtype="int64"):
    """Parity: paddle.randperm."""
    from .core import random as _r

    return _r.randperm(n, dtype)


def uniform(shape, dtype=None, min=-1.0, max=1.0):  # noqa: A002
    """Parity: paddle.uniform (note paddle's default range is [-1, 1),
    unlike rand's [0, 1))."""
    from .core import random as _r

    return _r.uniform(tuple(shape), dtype, min, max)


def normal(mean=0.0, std=1.0, shape=(1,)):
    """Parity: paddle.normal (mean/std leading, paddle argument order)."""
    from .core import random as _r

    return _r.normal(tuple(shape), None, mean, std)


def multinomial(x, num_samples=1, replacement=False):
    """Parity: paddle.multinomial — rows of ``x`` are (unnormalized)
    probabilities. Without replacement, asking for more samples than
    there are nonzero-probability categories raises (paddle semantics)."""
    from .core.random import next_rng_key

    x = _v(x)
    if not replacement:
        try:  # concrete probs: enforce the reference's error contract
            import numpy as _np

            nonzero = int((_np.asarray(x) > 0).sum(axis=-1).min())
            if num_samples > nonzero:
                raise ValueError(
                    f"multinomial(replacement=False): num_samples "
                    f"{num_samples} exceeds the {nonzero} nonzero-"
                    f"probability categories")
        except ValueError:
            raise
        except Exception:
            pass  # traced input: no host check possible
    logits = jnp.log(jnp.maximum(x, 1e-30))
    if replacement:
        # one vectorized draw: categorical broadcasts over a leading
        # sample axis
        out = jax.random.categorical(
            next_rng_key("default"), logits, axis=-1,
            shape=(num_samples,) + x.shape[:-1])
        out = jnp.moveaxis(out, 0, -1)
    else:
        # Gumbel top-k trick: iid gumbel noise + top-k == sampling
        # without replacement
        g = jax.random.gumbel(next_rng_key("default"), logits.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return out if x.ndim > 1 else out.reshape(-1)


# ---------------------------------------------------------------------------
# long-tail surface (parity: python/paddle/tensor/{math,manipulation,
# search,linalg}.py module-level APIs)
# ---------------------------------------------------------------------------
def mv(x, vec, name=None):
    return jnp.matmul(_v(x), _v(vec))


def bmm(x, y, name=None):
    x, y = _v(x), _v(y)
    if x.ndim != 3 or y.ndim != 3:
        raise ValueError("bmm expects 3-D inputs")
    return jnp.matmul(x, y)


def dist(x, y, p=2, name=None):
    """p-norm of (x - y) (paddle.dist, scalar)."""
    d = (_v(x) - _v(y)).ravel()
    p = float(p)
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    if p == 0:
        return jnp.sum(d != 0).astype(d.dtype)
    return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Pairwise p-distance between row vectors of x [..., m, d] and
    y [..., n, d] -> [..., m, n]."""
    x, y = _v(x), _v(y)
    diff = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.maximum(jnp.sum(diff * diff, axis=-1), 0.0))
    if p == float("inf"):
        return jnp.max(jnp.abs(diff), axis=-1)
    if p == 1.0:
        return jnp.sum(jnp.abs(diff), axis=-1)
    return jnp.power(jnp.sum(jnp.power(jnp.abs(diff), p), axis=-1), 1.0 / p)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return jnp.trapezoid(_v(y), x=_v(x), axis=axis)
    return jnp.trapezoid(_v(y), dx=1.0 if dx is None else dx, axis=axis)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = _v(y)
    y = jnp.moveaxis(y, axis, -1)
    if x is not None:
        xx = jnp.moveaxis(_v(x), axis, -1) if _v(x).ndim == y.ndim else _v(x)
        w = jnp.diff(xx, axis=-1)
    else:
        w = 1.0 if dx is None else dx
    steps = (y[..., 1:] + y[..., :-1]) * 0.5 * w
    return jnp.moveaxis(jnp.cumsum(steps, axis=-1), -1, axis)


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    return jnp.nanmedian(_v(x), axis=axis, keepdims=keepdim)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    """k-th SMALLEST (1-based, paddle semantics) -> (values, indices)."""
    x = _v(x)
    idx = jnp.argsort(x, axis=axis, stable=True)
    kth_idx = jnp.take(idx, k - 1, axis=axis)
    vals = jnp.take_along_axis(
        x, jnp.expand_dims(kth_idx, axis), axis=axis)
    if not keepdim:
        vals = jnp.squeeze(vals, axis)
        return vals, kth_idx
    return vals, jnp.expand_dims(kth_idx, axis)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    """Eager host-side op (dynamic output shape), like ``unique``."""
    import numpy as np

    a = np.asarray(_v(x))
    if axis is None:
        a = a.ravel()
        ax = 0
    else:
        ax = axis
    if a.shape[ax] == 0:
        change = np.zeros((0,), bool)
    else:
        moved = np.moveaxis(a, ax, 0)
        flat = moved.reshape(moved.shape[0], -1)
        change = np.concatenate(
            [[True], np.any(flat[1:] != flat[:-1], axis=1)])
    starts = np.flatnonzero(change)
    out = jnp.asarray(np.take(a, starts, axis=ax))
    res = [out]
    if return_inverse:
        res.append(jnp.asarray(np.cumsum(change) - 1))
    if return_counts:
        counts = np.diff(np.append(starts, a.shape[ax]))
        res.append(jnp.asarray(counts))
    return res[0] if len(res) == 1 else tuple(res)


def diagflat(x, offset=0, name=None):
    return jnp.diagflat(_v(x), k=offset)


def frexp(x, name=None):
    m, e = jnp.frexp(_v(x))
    return m, e.astype(jnp.int32)


def ldexp(x, y, name=None):
    return jnp.ldexp(_v(x), _v(y).astype(jnp.int32))


def lgamma(x, name=None):
    return jax.scipy.special.gammaln(_v(x))


def digamma(x, name=None):
    return jax.scipy.special.digamma(_v(x))


def polygamma(x, n, name=None):
    return jax.scipy.special.polygamma(n, _v(x))


def erfinv(x, name=None):
    return jax.lax.erf_inv(_v(x))


def i0(x, name=None):
    return jax.scipy.special.i0(_v(x))


def i0e(x, name=None):
    return jax.scipy.special.i0e(_v(x))


def i1(x, name=None):
    return jax.scipy.special.i1(_v(x))


def i1e(x, name=None):
    return jax.scipy.special.i1e(_v(x))


def sinc(x, name=None):
    return jnp.sinc(_v(x))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    hist, edges = jnp.histogramdd(
        _v(x), bins=bins, range=ranges, density=density,
        weights=None if weights is None else _v(weights))
    return hist, list(edges)


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return jnp.logspace(start, stop, int(num), base=base,
                        dtype=dtype and dtype_mod.convert_dtype(dtype))


def masked_scatter(x, mask, value, name=None):
    """Fill True positions of ``mask`` with elements of ``value`` taken
    in row-major order (paddle.masked_scatter)."""
    x, mask, value = _v(x), _v(mask), _v(value)
    mask = jnp.broadcast_to(mask, x.shape)
    flat_m = mask.ravel()
    take = jnp.cumsum(flat_m) - 1
    src = value.ravel()
    picked = jnp.take(src, jnp.clip(take, 0, src.size - 1))
    return jnp.where(flat_m, picked.astype(x.dtype),
                     x.ravel()).reshape(x.shape)


def index_put(x, indices, value, accumulate=False, name=None):
    x, value = _v(x), _v(value)
    idx = tuple(_v(i) for i in indices)
    if accumulate:
        return x.at[idx].add(value)
    return x.at[idx].set(value)


def unflatten(x, axis, shape, name=None):
    x = _v(x)
    axis = axis % x.ndim
    shape = tuple(int(s) for s in shape)
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        shape = tuple(x.shape[axis] // known if s == -1 else s
                      for s in shape)
    return x.reshape(x.shape[:axis] + shape + x.shape[axis + 1:])


def tensor_split(x, num_or_indices, axis=0, name=None):
    return list(jnp.array_split(_v(x), num_or_indices, axis=axis)) \
        if isinstance(num_or_indices, int) \
        else list(jnp.split(_v(x), list(num_or_indices), axis=axis))


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def hsplit(x, num_or_indices, name=None):
    x = _v(x)
    return tensor_split(x, num_or_indices, axis=0 if x.ndim == 1 else 1)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view (paddle.as_strided) as an explicit gather — jax
    arrays have no aliasing views, so this materializes."""
    x = _v(x).ravel()
    shape = tuple(int(s) for s in shape)
    idx = jnp.asarray(offset)
    for s, st in zip(shape, stride):
        idx = idx[..., None] + jnp.arange(s) * int(st)
    return jnp.take(x, idx.reshape(shape))


def unfold(x, axis, size, step, name=None):
    """Sliding windows along ``axis`` (paddle.Tensor.unfold): output
    gains a trailing window dim of length ``size``."""
    x = _v(x)
    axis = axis % x.ndim
    n = (x.shape[axis] - size) // step + 1
    starts = jnp.arange(n) * step
    win = starts[:, None] + jnp.arange(size)[None, :]   # [n, size]
    out = jnp.take(x, win.reshape(-1), axis=axis)
    out = out.reshape(x.shape[:axis] + (n, size) + x.shape[axis + 1:])
    return jnp.moveaxis(out, axis + 1, -1)


def view(x, shape_or_dtype, name=None):
    """paddle.view: reshape, or bitcast reinterpretation for a dtype."""
    x = _v(x)
    if isinstance(shape_or_dtype, (list, tuple)):
        return x.reshape(tuple(shape_or_dtype))
    dt = dtype_mod.convert_dtype(shape_or_dtype)
    if jnp.dtype(dt).itemsize == x.dtype.itemsize:
        return jax.lax.bitcast_convert_type(x, dt)
    # differing widths: fold/expand the trailing dim like paddle
    import numpy as np

    return jnp.asarray(np.asarray(x).view(np.dtype(dt)))


def view_as(x, other, name=None):
    return _v(x).reshape(_v(other).shape)


def is_tensor(x):
    return isinstance(x, (jax.Array, Parameter))


def rank(x, name=None):
    return jnp.asarray(jnp.ndim(_v(x)))


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    import numpy as np

    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


# inplace-spelled APIs: jax arrays are immutable, so these return the
# result (documented functional semantics; the trailing-underscore
# spelling exists for call-site parity)
def reshape_(x, shape, name=None):
    return jnp.reshape(_v(x), shape)


def squeeze_(x, axis=None, name=None):
    return jnp.squeeze(_v(x), axis)


def unsqueeze_(x, axis, name=None):
    return jnp.expand_dims(_v(x), axis)


def clip_(x, min=None, max=None, name=None):  # noqa: A002
    return jnp.clip(_v(x), min, max)


# ---- round-5 migration-surface sweep additions (parity:
# python/paddle/tensor/math.py, creation.py, attribute.py) ----

def mm(input, mat2, name=None):
    return jnp.matmul(_v(input), _v(mat2))


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    dt = dtype and dtype_mod.convert_dtype(dtype)
    return jnp.prod(_v(x), axis=axis, keepdims=keepdim, dtype=dt)


def tan(x, name=None):
    return jnp.tan(_v(x))


def sigmoid(x, name=None):
    return jax.nn.sigmoid(_v(x))


def erf(x, name=None):
    return jax.scipy.special.erf(_v(x))


def floor_divide(x, y, name=None):
    return jnp.floor_divide(_v(x), _v(y))


def remainder(x, y, name=None):
    return jnp.remainder(_v(x), _v(y))


def mod(x, y, name=None):
    return jnp.remainder(_v(x), _v(y))


def real(x, name=None):
    return jnp.real(_v(x))


def imag(x, name=None):
    return jnp.imag(_v(x))


def conj(x, name=None):
    return jnp.conj(_v(x))


def angle(x, name=None):
    return jnp.angle(_v(x))


def as_complex(x, name=None):
    """[..., 2] float -> [...] complex (parity: paddle.as_complex)."""
    x = _v(x)
    return jax.lax.complex(x[..., 0], x[..., 1])


def as_real(x, name=None):
    """[...] complex -> [..., 2] float (parity: paddle.as_real)."""
    x = _v(x)
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def broadcast_shape(x_shape, y_shape):
    import numpy as _np

    return list(_np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def take(x, index, mode="raise", name=None):
    """Flattened-index gather (parity: paddle.take; mode 'raise' clamps
    like 'clip' on TPU — data-dependent errors can't abort a compiled
    program; 'wrap' wraps)."""
    x, index = _v(x), _v(index)
    flat = x.reshape(-1)
    n = flat.shape[0]
    if mode == "wrap":
        index = jnp.mod(index, n)
    else:
        index = jnp.clip(index, -n, n - 1)
    return flat[index]


def index_add(x, index, axis, value, name=None):
    """out[index[i]] += value[i] along ``axis`` (parity:
    paddle.index_add)."""
    x, index, value = _v(x), _v(index), _v(value)
    axis = axis % x.ndim
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].add(value.astype(x.dtype))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None,
        name=None):
    return jnp.cov(_v(x), rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


def corrcoef(x, rowvar=True, name=None):
    return jnp.corrcoef(_v(x), rowvar=rowvar)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    return jnp.nanquantile(_v(x), q, axis=axis, keepdims=keepdim,
                           method=interpolation)
