"""Tensor creation & math API (parity: python/paddle/tensor/).

On TPU the tensor type IS ``jax.Array``; this module provides the
paddle-flavored creation/math surface over jax.numpy. No wrapper class: a
wrapper would break jax transforms and buy nothing — XLA is the dispatch
layer that paddle's pybind/phi stack (paddle/fluid/pybind/,
paddle/phi/api/) hand-builds on GPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .core import dtype as dtype_mod
from .core.parameter import Parameter


def _v(x):
    return x.value if isinstance(x, Parameter) else x


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    dt = dtype_mod.convert_dtype(dtype) if dtype is not None else None
    return jnp.asarray(_v(data), dtype=dt)


def zeros(shape, dtype=None):
    return jnp.zeros(shape, dtype_mod.convert_dtype(dtype))


def ones(shape, dtype=None):
    return jnp.ones(shape, dtype_mod.convert_dtype(dtype))


def full(shape, fill_value, dtype=None):
    return jnp.full(shape, fill_value, dtype_mod.convert_dtype(dtype))


def zeros_like(x, dtype=None):
    return jnp.zeros_like(_v(x), dtype=dtype and dtype_mod.convert_dtype(dtype))


def ones_like(x, dtype=None):
    return jnp.ones_like(_v(x), dtype=dtype and dtype_mod.convert_dtype(dtype))


def full_like(x, fill_value, dtype=None):
    return jnp.full_like(
        _v(x), fill_value, dtype=dtype and dtype_mod.convert_dtype(dtype)
    )


def arange(start, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    return jnp.arange(start, end, step, dtype and dtype_mod.convert_dtype(dtype))


def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, num, dtype=dtype and dtype_mod.convert_dtype(dtype))


def eye(n, m=None, dtype=None):
    return jnp.eye(n, m, dtype=dtype_mod.convert_dtype(dtype))


def empty(shape, dtype=None):
    return jnp.zeros(shape, dtype_mod.convert_dtype(dtype))


# math — re-export the jnp surface with paddle names
def _alias(fn):
    def wrapped(*args, **kwargs):
        args = tuple(_v(a) for a in args)
        return fn(*args, **kwargs)

    wrapped.__name__ = fn.__name__
    return wrapped


matmul = _alias(jnp.matmul)
add = _alias(jnp.add)
subtract = _alias(jnp.subtract)
multiply = _alias(jnp.multiply)
divide = _alias(jnp.divide)
pow = _alias(jnp.power)  # noqa: A001
sqrt = _alias(jnp.sqrt)
rsqrt = _alias(jax.lax.rsqrt)
exp = _alias(jnp.exp)
log = _alias(jnp.log)
abs = _alias(jnp.abs)  # noqa: A001
mean = _alias(jnp.mean)
sum = _alias(jnp.sum)  # noqa: A001
max = _alias(jnp.max)  # noqa: A001
min = _alias(jnp.min)  # noqa: A001
argmax = _alias(jnp.argmax)
argmin = _alias(jnp.argmin)
maximum = _alias(jnp.maximum)
minimum = _alias(jnp.minimum)
clip = _alias(jnp.clip)
reshape = _alias(jnp.reshape)
transpose = _alias(jnp.transpose)
squeeze = _alias(jnp.squeeze)
unsqueeze = _alias(jnp.expand_dims)
concat = _alias(jnp.concatenate)
stack = _alias(jnp.stack)
split = _alias(jnp.split)
where = _alias(jnp.where)
cast = _alias(lambda x, dtype: x.astype(dtype_mod.convert_dtype(dtype)))
tanh = _alias(jnp.tanh)
sin = _alias(jnp.sin)
cos = _alias(jnp.cos)
floor = _alias(jnp.floor)
ceil = _alias(jnp.ceil)
round = _alias(jnp.round)  # noqa: A001
sign = _alias(jnp.sign)
cumsum = _alias(jnp.cumsum)
cumprod = _alias(jnp.cumprod)
sort = _alias(jnp.sort)
argsort = _alias(jnp.argsort)
topk = _alias(jax.lax.top_k)
gather = _alias(lambda x, index, axis=0: jnp.take(x, index, axis=axis))
einsum = _alias(jnp.einsum)
tril = _alias(jnp.tril)
triu = _alias(jnp.triu)
flatten = _alias(jnp.ravel)
isnan = _alias(jnp.isnan)
isinf = _alias(jnp.isinf)
isfinite = _alias(jnp.isfinite)
equal = _alias(jnp.equal)
not_equal = _alias(jnp.not_equal)
greater_than = _alias(jnp.greater)
less_than = _alias(jnp.less)
logical_and = _alias(jnp.logical_and)
logical_or = _alias(jnp.logical_or)
logical_not = _alias(jnp.logical_not)
all = _alias(jnp.all)  # noqa: A001
any = _alias(jnp.any)  # noqa: A001
square = _alias(jnp.square)
log_softmax = _alias(jax.nn.log_softmax)
softmax = _alias(jax.nn.softmax)
var = _alias(jnp.var)
std = _alias(jnp.std)
norm = _alias(jnp.linalg.norm)
dot = _alias(jnp.dot)
outer = _alias(jnp.outer)
roll = _alias(jnp.roll)
flip = _alias(jnp.flip)
tile = _alias(jnp.tile)
repeat_interleave = _alias(jnp.repeat)
broadcast_to = _alias(jnp.broadcast_to)
expand = _alias(jnp.broadcast_to)
take_along_axis = _alias(jnp.take_along_axis)
index_select = _alias(lambda x, index, axis=0: jnp.take(x, index, axis=axis))
masked_select = _alias(lambda x, mask: x[mask])
numel = _alias(jnp.size)
diag = _alias(jnp.diag)
