"""Topology: the hybrid-parallel device mesh.

Parity: fleet/base/topology.py — ``CommunicateTopology`` +
``HybridCommunicateGroup`` build an nd-grid over ranks in order
[dp, pp, sharding, sep, mp] and create a NCCL group per axis per slice.

TPU-native: there are no process groups to create — the grid IS a
``jax.sharding.Mesh`` and every "group collective" is a GSPMD/shard_map
collective over a named mesh axis. The class below keeps the Fleet query
API (get_model_parallel_world_size / *_rank / groups) so trainer-level
code ports over unchanged, while ``mesh`` is the object the compiler
consumes. Axis name mapping: dp→"dp", pp→"pp", sharding→"fsdp",
sep→"sep", mp→"tp".
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from .strategy import DistributedStrategy

AXIS_ORDER = ("dp", "pp", "fsdp", "ep", "sep", "tp")

_global_hcg: Optional["HybridCommunicateGroup"] = None


class CommGroup:
    """A slice of mesh ranks along one axis (parity: the object
    paddle.distributed.new_group returns; here it carries the axis name
    that shard_map collectives use)."""

    def __init__(self, axis: str, size: int, rank: int, ranks: List[int]):
        self.axis = axis
        self.nranks = size
        self.rank = rank
        self.ranks = ranks

    @property
    def world_size(self):
        return self.nranks

    def __repr__(self):
        return f"CommGroup(axis={self.axis}, nranks={self.nranks}, rank={self.rank})"


class HybridCommunicateGroup:
    def __init__(
        self,
        strategy: Optional[DistributedStrategy] = None,
        devices: Optional[Sequence] = None,
        *,
        dp: int = None,
        tp: int = None,
        pp: int = None,
        fsdp: int = None,
        ep: int = None,
        sep: int = None,
        rank: int = 0,
    ):
        strategy = strategy or DistributedStrategy()
        h = strategy.hybrid_configs
        self.strategy = strategy
        self._dp = dp if dp is not None else h.dp_degree
        self._tp = tp if tp is not None else h.mp_degree
        self._pp = pp if pp is not None else h.pp_degree
        self._fsdp = fsdp if fsdp is not None else h.sharding_degree
        self._ep = ep if ep is not None else h.ep_degree
        self._sep = sep if sep is not None else h.sep_degree

        if devices is None:
            devices = jax.devices()
        need = (self._dp * self._pp * self._fsdp * self._ep
                * self._sep * self._tp)
        if need == 0:
            raise ValueError("degrees must be >= 1")
        if len(devices) < need:
            raise ValueError(
                f"need {need} devices for "
                f"dp{self._dp}×pp{self._pp}×fsdp{self._fsdp}"
                f"×ep{self._ep}×sep{self._sep}"
                f"×tp{self._tp}, have {len(devices)}"
            )
        if len(devices) > need and self._dp == h.dp_degree and dp is None:
            # absorb extra devices into dp (parity: launch auto-degree)
            self._dp = len(devices) // (
                self._pp * self._fsdp * self._ep * self._sep * self._tp)
            need = (self._dp * self._pp * self._fsdp * self._ep
                    * self._sep * self._tp)
        grid = np.array(devices[:need]).reshape(
            self._dp, self._pp, self._fsdp, self._ep, self._sep, self._tp
        )
        self.mesh = Mesh(grid, AXIS_ORDER)
        self.global_rank = rank
        self.nranks = need

    # ------------------------------------------------------------------
    # coordinates of this process's "rank" within the logical grid. In
    # SPMD execution all coordinates exist simultaneously; these queries
    # serve host-side logic (data sharding, checkpoint naming, logging).
    def _coord(self) -> Tuple[int, ...]:
        shape = (self._dp, self._pp, self._fsdp, self._ep,
                 self._sep, self._tp)
        return tuple(np.unravel_index(self.global_rank % self.nranks, shape))

    def topology(self):
        return {
            "dp": self._dp, "pp": self._pp, "fsdp": self._fsdp,
            "ep": self._ep, "sep": self._sep, "tp": self._tp,
        }

    # fleet-parity queries ---------------------------------------------
    def get_data_parallel_world_size(self):
        return self._dp

    def get_data_parallel_rank(self):
        return self._coord()[0]

    def get_pipe_parallel_world_size(self):
        return self._pp

    def get_stage_id(self):
        return self._coord()[1]

    def get_sharding_parallel_world_size(self):
        return self._fsdp

    def get_sharding_parallel_rank(self):
        return self._coord()[2]

    def get_expert_parallel_world_size(self):
        return self._ep

    def get_expert_parallel_rank(self):
        return self._coord()[3]

    def get_sep_parallel_world_size(self):
        return self._sep

    def get_sep_parallel_rank(self):
        return self._coord()[4]

    def get_model_parallel_world_size(self):
        return self._tp

    def get_model_parallel_rank(self):
        return self._coord()[5]

    def _group(self, axis: str) -> CommGroup:
        sizes = self.topology()
        coord = dict(zip(AXIS_ORDER, self._coord()))
        size = sizes[axis]
        rank = coord[axis]
        # enumerate global ranks in this slice
        shape = (self._dp, self._pp, self._fsdp, self._ep,
                 self._sep, self._tp)
        idx = [coord[a] for a in AXIS_ORDER]
        axis_i = AXIS_ORDER.index(axis)
        ranks = []
        for j in range(size):
            idx2 = list(idx)
            idx2[axis_i] = j
            ranks.append(int(np.ravel_multi_index(idx2, shape)))
        return CommGroup(axis, size, rank, ranks)

    def get_data_parallel_group(self):
        return self._group("dp")

    def get_model_parallel_group(self):
        return self._group("tp")

    def get_pipe_parallel_group(self):
        return self._group("pp")

    def get_sharding_parallel_group(self):
        return self._group("fsdp")

    def get_sep_parallel_group(self):
        return self._group("sep")

    def get_expert_parallel_group(self):
        return self._group("ep")

    # is_first/last stage for PP scheduling
    @property
    def is_first_stage(self):
        return self.get_stage_id() == 0

    @property
    def is_last_stage(self):
        return self.get_stage_id() == self._pp - 1


def build_mesh(
    *,
    dp: int = 1,
    pp: int = 1,
    fsdp: int = 1,
    ep: int = 1,
    sep: int = 1,
    tp: int = 1,
    devices=None,
) -> Mesh:
    """Direct mesh construction for code that doesn't need the HCG shim."""
    if devices is None:
        devices = jax.devices()
    need = dp * pp * fsdp * ep * sep * tp
    from ..errors import PreconditionNotMetError, enforce_ge

    enforce_ge(len(devices), need,
               f"available devices (mesh dp={dp} pp={pp} fsdp={fsdp} "
               f"ep={ep} sep={sep} tp={tp} needs {need})",
               PreconditionNotMetError)
    grid = np.array(devices[:need]).reshape(dp, pp, fsdp, ep, sep, tp)
    return Mesh(grid, AXIS_ORDER)


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    global _global_hcg
    _global_hcg = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _global_hcg


def fleet_init(strategy: Optional[DistributedStrategy] = None, devices=None):
    """Parity: fleet.init(is_collective=True, strategy=...) — builds the
    global HCG/mesh from the strategy's hybrid_configs."""
    hcg = HybridCommunicateGroup(strategy, devices=devices)
    set_hybrid_communicate_group(hcg)
    return hcg
