"""Mixture-of-Experts with expert parallelism.

Parity: python/paddle/incubate/distributed/models/moe/ — ``MoELayer``
with GShard/Switch/Naive gates, capacity-factor dispatch, aux load-balance
loss — plus the C++ ``global_scatter``/``global_gather`` all-to-all
collective ops (paddle/fluid/operators/collective/global_scatter_op.*).

TPU-native inversion: the reference routes tokens with explicit ragged
all-to-alls. Here dispatch/combine are *static-shape einsums* against
one-hot capacity tensors (the GShard formulation, which is what maps onto
the MXU) and the expert dim of the batched expert weights is sharded over
a mesh axis — GSPMD turns the dispatch einsum into exactly the all-to-all
the reference hand-codes, overlapped by XLA.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core import initializer as I
from ..core.module import Layer
from ..nn import functional as F
from .sharding import shard_activation


def _top2_gating(logits, capacity: int, rng_key=None):
    """GShard top-2 gating. logits: [tokens, experts] fp32.

    Returns combine [t, e, c], dispatch mask [t, e, c] (bool), aux loss,
    and the dropped-token fraction (routed assignments that exceeded
    expert capacity — the quantity the reference logs to detect
    too-small capacity_factor).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    gate1_idx = jnp.argmax(probs, axis=-1)  # [t]
    mask1 = jax.nn.one_hot(gate1_idx, e, dtype=probs.dtype)
    # aux load-balance loss (GShard eq.4): e * mean(density * density_proxy)
    density = jnp.mean(mask1, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * e

    probs_wo1 = probs * (1.0 - mask1)
    gate2_idx = jnp.argmax(probs_wo1, axis=-1)
    mask2 = jax.nn.one_hot(gate2_idx, e, dtype=probs.dtype)

    # positions within each expert (cumsum over tokens)
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1  # [t, e]
    pos2 = (jnp.cumsum(mask2, axis=0) - mask2 +
            jnp.sum(mask1, axis=0, keepdims=True)) * mask2
    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)

    g1 = jnp.sum(probs * keep1, axis=-1)  # [t]
    g2 = jnp.sum(probs * keep2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    routed = jnp.sum(mask1) + jnp.sum(
        mask2 * (probs_wo1.max(-1) > 0)[:, None])
    kept = jnp.sum(keep1) + jnp.sum(keep2)
    drop_fraction = 1.0 - kept / jnp.maximum(routed, 1.0)

    p1 = jnp.sum(pos1 * keep1, axis=-1).astype(jnp.int32)  # [t]
    p2 = jnp.sum(pos2 * keep2, axis=-1).astype(jnp.int32)
    cap1 = jax.nn.one_hot(p1, capacity, dtype=probs.dtype)  # [t, c]
    cap2 = jax.nn.one_hot(p2, capacity, dtype=probs.dtype)
    combine = (
        g1[:, None, None] * keep1[:, :, None] * cap1[:, None, :]
        + g2[:, None, None] * keep2[:, :, None] * cap2[:, None, :]
    )  # [t, e, c]
    dispatch = combine > 0.0
    return combine, dispatch, aux, drop_fraction


def _switch_gating(logits, capacity: int):
    """Switch-transformer top-1 gating."""
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    mask = jax.nn.one_hot(idx, e, dtype=probs.dtype)
    density = jnp.mean(mask, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * e
    pos = jnp.cumsum(mask, axis=0) * mask - mask
    keep = mask * (pos < capacity)
    drop_fraction = 1.0 - jnp.sum(keep) / jnp.maximum(jnp.sum(mask), 1.0)
    g = jnp.sum(probs * keep, axis=-1)
    p = jnp.sum(pos * keep, axis=-1).astype(jnp.int32)
    cap = jax.nn.one_hot(p, capacity, dtype=probs.dtype)
    combine = g[:, None, None] * keep[:, :, None] * cap[:, None, :]
    return combine, combine > 0.0, aux, drop_fraction


class ExpertFFN(Layer):
    """Batched expert FFN: weights [E, in, hidden], [E, hidden, in] with
    the expert dim sharded over ``expert_axis``."""

    def __init__(self, num_experts, d_model, d_hidden, expert_axis="ep",
                 activation="gelu", init_std=0.02):
        super().__init__()
        init = I.Normal(0.0, init_std)
        self.w1 = self.create_parameter(
            (num_experts, d_model, d_hidden), default_initializer=init,
            spec=(expert_axis, None, "tp"),
        )
        self.w2 = self.create_parameter(
            (num_experts, d_hidden, d_model), default_initializer=init,
            spec=(expert_axis, "tp", None),
        )
        self.b1 = self.create_parameter(
            (num_experts, d_hidden), is_bias=True, spec=(expert_axis, "tp")
        )
        self.b2 = self.create_parameter(
            (num_experts, d_model), is_bias=True, spec=(expert_axis, None)
        )
        self.act = getattr(F, activation)

    def forward(self, x):
        # x: [E, cap_total, d_model]
        h = jnp.einsum("ecm,emh->ech", x, self.w1.value) + self.b1.value[:, None]
        h = self.act(h)
        return jnp.einsum("ech,ehm->ecm", h, self.w2.value) + self.b2.value[:, None]


class MoELayer(Layer):
    """Parity: incubate MoELayer(gate={...}, experts=[...]).

    forward(x: [batch, seq, d_model]) -> (y, aux_loss). Stores the last
    aux loss in ``self.last_aux_loss`` for trainers that prefer the
    paddle-style side-channel.
    """

    def __init__(
        self,
        d_model: int,
        num_experts: int,
        d_hidden: Optional[int] = None,
        gate: str = "gshard",
        top_k: int = 2,
        capacity_factor: Optional[float] = None,
        expert_axis: str = "ep",
        aux_loss_weight: float = 1e-2,
    ):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.gate_type = gate
        self.top_k = 1 if gate == "switch" else top_k
        if capacity_factor is None:
            # layer default rides PT_FLAGS_moe_capacity_factor (1.25)
            from .. import flags

            capacity_factor = float(flags.flag("moe_capacity_factor"))
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = aux_loss_weight
        self.gate_weight = self.create_parameter(
            (d_model, num_experts),
            default_initializer=I.Normal(0.0, 0.02),
        )
        self.expert_axis = expert_axis
        self.experts = ExpertFFN(
            num_experts, d_model, d_hidden or 4 * d_model, expert_axis
        )
        self.last_aux_loss = 0.0
        self.last_drop_fraction = 0.0  # scalar jnp: routed-but-dropped share

    def capacity(self, tokens: int) -> int:
        cap = int(self.capacity_factor * tokens * self.top_k / self.num_experts)
        return max(cap, 4)

    def forward(self, x):
        b, s, m = x.shape
        tokens = b * s
        xf = x.reshape(tokens, m)
        logits = (xf.astype(jnp.float32) @
                  self.gate_weight.value.astype(jnp.float32))
        cap = self.capacity(tokens)
        if self.gate_type == "switch":
            combine, dispatch, aux, dropped = _switch_gating(logits, cap)
        else:
            combine, dispatch, aux, dropped = _top2_gating(logits, cap)
        combine = combine.astype(x.dtype)
        # dispatch: [t, e, c] x [t, m] -> [e, c, m]; GSPMD inserts the
        # token→expert all-to-all here (expert dim sharded)
        expert_in = jnp.einsum(
            "tec,tm->ecm", dispatch.astype(x.dtype), xf
        )
        expert_in = shard_activation(expert_in, self.expert_axis, None, None)
        expert_out = self.experts(expert_in)
        expert_out = shard_activation(expert_out, self.expert_axis, None, None)
        y = jnp.einsum("tec,ecm->tm", combine, expert_out)
        self.last_aux_loss = aux * self.aux_loss_weight
        self.last_drop_fraction = dropped
        return y.reshape(b, s, m), self.last_aux_loss


def _dropless_topk_gating(logits, top_k: int):
    """Top-k gating with NO capacity clamp: every routed token is
    processed. Returns (expert_idx [t, k], gates [t, k], aux)."""
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    gates, expert_idx = jax.lax.top_k(probs, top_k)
    gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
    # load-balance aux (GShard form on the top-1 assignment)
    mask1 = jax.nn.one_hot(expert_idx[:, 0], e, dtype=probs.dtype)
    aux = jnp.sum(jnp.mean(mask1, 0) * jnp.mean(probs, 0)) * e
    return expert_idx, gates, aux


def dropless_moe_apply(x, expert_idx, gates, w1, b1, w2, b2, act):
    """MegaBlocks-style dropless dispatch, TPU-native form: sort the
    (token, expert) assignments by expert and run ONE grouped matmul per
    projection via ``jax.lax.ragged_dot`` — XLA's grouped-GEMM primitive
    tiles the ragged group dim onto the MXU without materializing
    one-hot dispatch tensors or dropping overflow tokens.

    x: [t, m]; expert_idx/gates: [t, k]; w1: [E, m, h]; w2: [E, h, m].
    Parity: the reference's dropless/"no-token-dropping" MoE modes
    (incubate moe capacity_factor=None paths).
    """
    t, k = expert_idx.shape
    E = w1.shape[0]
    flat_e = expert_idx.reshape(-1)             # [t*k]
    order = jnp.argsort(flat_e)                 # stable
    inv = jnp.argsort(order)
    xs = jnp.repeat(x, k, axis=0)[order]        # [t*k, m] sorted by expert
    group_sizes = jnp.bincount(flat_e, length=E).astype(jnp.int32)
    h = jax.lax.ragged_dot(xs, w1, group_sizes)
    h = h + jnp.repeat(b1, group_sizes, axis=0,
                       total_repeat_length=t * k)
    h = act(h)
    y = jax.lax.ragged_dot(h, w2, group_sizes)
    y = y + jnp.repeat(b2, group_sizes, axis=0,
                       total_repeat_length=t * k)
    y = y[inv].reshape(t, k, -1)                # unsort, [t, k, m]
    return jnp.sum(y * gates[..., None].astype(y.dtype), axis=1)


def dropless_moe_ep_apply(xf, gate_weight, w1, b1, w2, b2, act, top_k,
                          mesh, ep_axis="ep"):
    """Distributed dropless dispatch over the ``ep`` mesh axis.

    Parity: the reference's ``global_scatter`` → per-expert FFN →
    ``global_gather`` pipeline (paddle/fluid/operators/collective/
    global_scatter_op.*, incubate moe) — tokens travel to the shard
    owning their expert, are processed in ONE contiguous grouped matmul,
    and travel back.

    TPU-native form (static shapes, one SPMD program):
      1. route + stable-sort local (token, k) assignments by expert id;
      2. counts → the per-destination segment sizes; a dense
         ``lax.all_to_all`` exchanges STATIC per-source slots of
         N = t_local·top_k rows — every routed token always has a seat,
         so the exchange is dropless *by construction* (the reference's
         ragged NCCL alltoallv becomes a fixed-shape ICI collective;
         ``lax.ragged_all_to_all`` sends only the filled prefixes and is
         the drop-in TPU bandwidth upgrade, but XLA:CPU has no kernel
         for it, and CI runs on the CPU mesh);
      3. received rows re-sort into per-local-expert contiguous groups →
         ``lax.ragged_dot`` (padding rows ride a zero-weight dummy
         expert);
      4. reverse all_to_all returns outputs to the source's sorted
         positions; unsort; combine with gates.

    xf: [t, m] with the token dim sharded over ``ep_axis`` (t % ep == 0);
    w1/b1/w2/b2: [E, ...] sharded over ``ep_axis`` on the expert dim.
    Mesh axes other than ``ep_axis`` stay under GSPMD (shard_map
    ``axis_names``), so EP composes with dp/fsdp/tp.
    Returns (y [t, m], aux scalar) with aux computed from GLOBAL routing
    statistics (pmean over ep).
    """
    from jax import lax

    from ..jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    ep = mesh.shape[ep_axis]
    E = w1.shape[0]
    if E % ep:
        raise ValueError(f"num_experts {E} must divide ep degree {ep}")
    e_loc = E // ep

    def body(x_loc, gw, w1_loc, b1_loc, w2_loc, b2_loc):
        n = x_loc.shape[0] * top_k
        logits = x_loc.astype(jnp.float32) @ gw.astype(jnp.float32)
        expert_idx, gates, _ = _dropless_topk_gating(logits, top_k)
        # aux from global stats: pmean of per-shard densities == global
        # means (equal token counts per shard)
        probs = jax.nn.softmax(logits, axis=-1)
        mask1 = jax.nn.one_hot(expert_idx[:, 0], E, dtype=probs.dtype)
        density = lax.pmean(jnp.mean(mask1, 0), ep_axis)
        proxy = lax.pmean(jnp.mean(probs, 0), ep_axis)
        aux = jnp.sum(density * proxy) * E

        flat_e = expert_idx.reshape(-1)
        order = jnp.argsort(flat_e, stable=True)
        sorted_e = flat_e[order]
        xs = jnp.repeat(x_loc, top_k, axis=0)[order]

        counts = jnp.bincount(flat_e, length=E).astype(jnp.int32)
        send_sizes = counts.reshape(ep, e_loc).sum(1).astype(jnp.int32)
        input_offsets = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(send_sizes)[:-1]])

        # ragged exchange: destination segments pack into static
        # per-source slots (the public collective owns this machinery)
        from .collective import alltoall_single_in

        recv_buf, _ = alltoall_single_in(
            xs, send_sizes, axis=ep_axis, slot_rows=n)       # [ep, n, m]
        cmat = lax.all_to_all(                               # [ep, e_loc]
            counts.reshape(ep, e_loc), ep_axis, 0, 0)

        b_rows = ep * n
        buf = recv_buf.reshape(b_rows, -1)
        vals = jnp.concatenate(
            [jnp.arange(e_loc), jnp.array([e_loc])]).astype(jnp.int32)

        def block_ids(crow):
            cnt = jnp.concatenate(
                [crow, (n - crow.sum())[None]]).astype(jnp.int32)
            return jnp.repeat(vals, cnt, total_repeat_length=n)

        ids = jax.vmap(block_ids)(cmat).reshape(b_rows)
        order2 = jnp.argsort(ids, stable=True)
        inv2 = jnp.argsort(order2, stable=True)
        xs2 = buf[order2]
        per_e = cmat.sum(0)
        gsz = jnp.concatenate(
            [per_e, (b_rows - per_e.sum())[None]]).astype(jnp.int32)

        w1e = jnp.concatenate(
            [w1_loc, jnp.zeros((1,) + w1_loc.shape[1:], w1_loc.dtype)])
        b1e = jnp.concatenate(
            [b1_loc, jnp.zeros((1,) + b1_loc.shape[1:], b1_loc.dtype)])
        w2e = jnp.concatenate(
            [w2_loc, jnp.zeros((1,) + w2_loc.shape[1:], w2_loc.dtype)])
        b2e = jnp.concatenate(
            [b2_loc, jnp.zeros((1,) + b2_loc.shape[1:], b2_loc.dtype)])

        h = lax.ragged_dot(xs2, w1e, gsz)
        h = h + jnp.repeat(b1e, gsz, axis=0, total_repeat_length=b_rows)
        h = act(h)
        y2 = lax.ragged_dot(h, w2e, gsz)
        y2 = y2 + jnp.repeat(b2e, gsz, axis=0, total_repeat_length=b_rows)
        # padding rows picked up dummy-expert bias: zero them
        y2 = jnp.where((ids[order2] < e_loc)[:, None], y2, 0.0)

        y_ret = lax.all_to_all(
            y2[inv2].reshape(ep, n, -1), ep_axis, 0, 0)
        # row r of the sorted order returned from dest j = e//e_loc at
        # slot r - input_offsets[j]
        j_r = (sorted_e // e_loc).astype(jnp.int32)
        p_r = jnp.arange(n) - input_offsets[j_r]
        y_sorted = y_ret[j_r, p_r]
        inv = jnp.argsort(order, stable=True)
        y = y_sorted[inv].reshape(-1, top_k, y_sorted.shape[-1])
        return (jnp.sum(y * gates[..., None].astype(y.dtype), axis=1),
                aux)

    f = shard_map(
        body, mesh=mesh,
        in_specs=(P(ep_axis), P(), P(ep_axis), P(ep_axis), P(ep_axis),
                  P(ep_axis)),
        out_specs=(P(ep_axis), P()),
        axis_names=frozenset({ep_axis}),
        check_vma=False,
    )
    return f(xf, gate_weight, w1, b1, w2, b2)


class DroplessMoELayer(MoELayer):
    """MoELayer with exact (no-drop) routing via grouped matmuls.

    Single shard (or ep degree 1): MegaBlocks-style sort + one
    ``ragged_dot`` per projection, no [t, e, c] dispatch tensors.
    With an active mesh whose ``ep`` degree > 1: sort-based all-to-all
    dispatch over the ep axis (``dropless_moe_ep_apply``) — dropless
    and expert-parallel compose, replacing the round-3 replicated-only
    constraint. last_drop_fraction is always 0 by construction.
    """

    def forward(self, x):
        from .sharding import current_mesh

        b, s, m = x.shape
        xf = x.reshape(b * s, m)
        mesh = current_mesh()
        ep = (mesh.shape.get(self.expert_axis, 1)
              if mesh is not None and self.expert_axis else 1)
        if ep > 1:
            if (b * s) % ep:
                from ..errors import InvalidArgumentError

                raise InvalidArgumentError(
                    f"dropless EP: token count {b * s} must be "
                    f"divisible by ep degree {ep}")
            y, aux = dropless_moe_ep_apply(
                xf, self.gate_weight.value,
                self.experts.w1.value, self.experts.b1.value,
                self.experts.w2.value, self.experts.b2.value,
                self.experts.act, self.top_k, mesh, self.expert_axis)
        else:
            logits = (xf.astype(jnp.float32) @
                      self.gate_weight.value.astype(jnp.float32))
            expert_idx, gates, aux = _dropless_topk_gating(
                logits, self.top_k)
            y = dropless_moe_apply(
                xf, expert_idx, gates,
                self.experts.w1.value, self.experts.b1.value,
                self.experts.w2.value, self.experts.b2.value,
                self.experts.act)
        self.last_aux_loss = aux * self.aux_loss_weight
        self.last_drop_fraction = jnp.zeros(())
        return y.reshape(b, s, m), self.last_aux_loss
