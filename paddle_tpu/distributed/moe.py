"""Mixture-of-Experts with expert parallelism.

Parity: python/paddle/incubate/distributed/models/moe/ — ``MoELayer``
with GShard/Switch/Naive gates, capacity-factor dispatch, aux load-balance
loss — plus the C++ ``global_scatter``/``global_gather`` all-to-all
collective ops (paddle/fluid/operators/collective/global_scatter_op.*).

TPU-native inversion: the reference routes tokens with explicit ragged
all-to-alls. Here dispatch/combine are *static-shape einsums* against
one-hot capacity tensors (the GShard formulation, which is what maps onto
the MXU) and the expert dim of the batched expert weights is sharded over
a mesh axis — GSPMD turns the dispatch einsum into exactly the all-to-all
the reference hand-codes, overlapped by XLA.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core import initializer as I
from ..core.module import Layer
from ..nn import functional as F
from .sharding import shard_activation


def _top2_gating(logits, capacity: int, rng_key=None):
    """GShard top-2 gating. logits: [tokens, experts] fp32.

    Returns combine [t, e, c], dispatch mask [t, e, c] (bool), aux loss.
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    gate1_idx = jnp.argmax(probs, axis=-1)  # [t]
    mask1 = jax.nn.one_hot(gate1_idx, e, dtype=probs.dtype)
    # aux load-balance loss (GShard eq.4): e * mean(density * density_proxy)
    density = jnp.mean(mask1, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * e

    probs_wo1 = probs * (1.0 - mask1)
    gate2_idx = jnp.argmax(probs_wo1, axis=-1)
    mask2 = jax.nn.one_hot(gate2_idx, e, dtype=probs.dtype)

    # positions within each expert (cumsum over tokens)
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1  # [t, e]
    pos2 = (jnp.cumsum(mask2, axis=0) - mask2 +
            jnp.sum(mask1, axis=0, keepdims=True)) * mask2
    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)

    g1 = jnp.sum(probs * keep1, axis=-1)  # [t]
    g2 = jnp.sum(probs * keep2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    p1 = jnp.sum(pos1 * keep1, axis=-1).astype(jnp.int32)  # [t]
    p2 = jnp.sum(pos2 * keep2, axis=-1).astype(jnp.int32)
    cap1 = jax.nn.one_hot(p1, capacity, dtype=probs.dtype)  # [t, c]
    cap2 = jax.nn.one_hot(p2, capacity, dtype=probs.dtype)
    combine = (
        g1[:, None, None] * keep1[:, :, None] * cap1[:, None, :]
        + g2[:, None, None] * keep2[:, :, None] * cap2[:, None, :]
    )  # [t, e, c]
    dispatch = combine > 0.0
    return combine, dispatch, aux


def _switch_gating(logits, capacity: int):
    """Switch-transformer top-1 gating."""
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    mask = jax.nn.one_hot(idx, e, dtype=probs.dtype)
    density = jnp.mean(mask, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * e
    pos = jnp.cumsum(mask, axis=0) * mask - mask
    keep = mask * (pos < capacity)
    g = jnp.sum(probs * keep, axis=-1)
    p = jnp.sum(pos * keep, axis=-1).astype(jnp.int32)
    cap = jax.nn.one_hot(p, capacity, dtype=probs.dtype)
    combine = g[:, None, None] * keep[:, :, None] * cap[:, None, :]
    return combine, combine > 0.0, aux


class ExpertFFN(Layer):
    """Batched expert FFN: weights [E, in, hidden], [E, hidden, in] with
    the expert dim sharded over ``expert_axis``."""

    def __init__(self, num_experts, d_model, d_hidden, expert_axis="fsdp",
                 activation="gelu", init_std=0.02):
        super().__init__()
        init = I.Normal(0.0, init_std)
        self.w1 = self.create_parameter(
            (num_experts, d_model, d_hidden), default_initializer=init,
            spec=(expert_axis, None, "tp"),
        )
        self.w2 = self.create_parameter(
            (num_experts, d_hidden, d_model), default_initializer=init,
            spec=(expert_axis, "tp", None),
        )
        self.b1 = self.create_parameter(
            (num_experts, d_hidden), is_bias=True, spec=(expert_axis, "tp")
        )
        self.b2 = self.create_parameter(
            (num_experts, d_model), is_bias=True, spec=(expert_axis, None)
        )
        self.act = getattr(F, activation)

    def forward(self, x):
        # x: [E, cap_total, d_model]
        h = jnp.einsum("ecm,emh->ech", x, self.w1.value) + self.b1.value[:, None]
        h = self.act(h)
        return jnp.einsum("ech,ehm->ecm", h, self.w2.value) + self.b2.value[:, None]


class MoELayer(Layer):
    """Parity: incubate MoELayer(gate={...}, experts=[...]).

    forward(x: [batch, seq, d_model]) -> (y, aux_loss). Stores the last
    aux loss in ``self.last_aux_loss`` for trainers that prefer the
    paddle-style side-channel.
    """

    def __init__(
        self,
        d_model: int,
        num_experts: int,
        d_hidden: Optional[int] = None,
        gate: str = "gshard",
        top_k: int = 2,
        capacity_factor: float = 1.25,
        expert_axis: str = "fsdp",
        aux_loss_weight: float = 1e-2,
    ):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.gate_type = gate
        self.top_k = 1 if gate == "switch" else top_k
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = aux_loss_weight
        self.gate_weight = self.create_parameter(
            (d_model, num_experts),
            default_initializer=I.Normal(0.0, 0.02),
        )
        self.experts = ExpertFFN(
            num_experts, d_model, d_hidden or 4 * d_model, expert_axis
        )
        self.last_aux_loss = 0.0

    def capacity(self, tokens: int) -> int:
        cap = int(self.capacity_factor * tokens * self.top_k / self.num_experts)
        return max(cap, 4)

    def forward(self, x):
        b, s, m = x.shape
        tokens = b * s
        xf = x.reshape(tokens, m)
        logits = (xf.astype(jnp.float32) @
                  self.gate_weight.value.astype(jnp.float32))
        cap = self.capacity(tokens)
        if self.gate_type == "switch":
            combine, dispatch, aux = _switch_gating(logits, cap)
        else:
            combine, dispatch, aux = _top2_gating(logits, cap)
        combine = combine.astype(x.dtype)
        # dispatch: [t, e, c] x [t, m] -> [e, c, m]; GSPMD inserts the
        # token→expert all-to-all here (expert dim sharded)
        expert_in = jnp.einsum(
            "tec,tm->ecm", dispatch.astype(x.dtype), xf
        )
        expert_in = shard_activation(expert_in, "fsdp", None, None)
        expert_out = self.experts(expert_in)
        expert_out = shard_activation(expert_out, "fsdp", None, None)
        y = jnp.einsum("tec,ecm->tm", combine, expert_out)
        self.last_aux_loss = aux * self.aux_loss_weight
        return y.reshape(b, s, m), self.last_aux_loss
