"""Mixture-of-Experts with expert parallelism.

Parity: python/paddle/incubate/distributed/models/moe/ — ``MoELayer``
with GShard/Switch/Naive gates, capacity-factor dispatch, aux load-balance
loss — plus the C++ ``global_scatter``/``global_gather`` all-to-all
collective ops (paddle/fluid/operators/collective/global_scatter_op.*).

TPU-native inversion: the reference routes tokens with explicit ragged
all-to-alls. Here dispatch/combine are *static-shape einsums* against
one-hot capacity tensors (the GShard formulation, which is what maps onto
the MXU) and the expert dim of the batched expert weights is sharded over
a mesh axis — GSPMD turns the dispatch einsum into exactly the all-to-all
the reference hand-codes, overlapped by XLA.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core import initializer as I
from ..core.module import Layer
from ..nn import functional as F
from .sharding import shard_activation


def _top2_gating(logits, capacity: int, rng_key=None):
    """GShard top-2 gating. logits: [tokens, experts] fp32.

    Returns combine [t, e, c], dispatch mask [t, e, c] (bool), aux loss,
    and the dropped-token fraction (routed assignments that exceeded
    expert capacity — the quantity the reference logs to detect
    too-small capacity_factor).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    gate1_idx = jnp.argmax(probs, axis=-1)  # [t]
    mask1 = jax.nn.one_hot(gate1_idx, e, dtype=probs.dtype)
    # aux load-balance loss (GShard eq.4): e * mean(density * density_proxy)
    density = jnp.mean(mask1, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * e

    probs_wo1 = probs * (1.0 - mask1)
    gate2_idx = jnp.argmax(probs_wo1, axis=-1)
    mask2 = jax.nn.one_hot(gate2_idx, e, dtype=probs.dtype)

    # positions within each expert (cumsum over tokens)
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1  # [t, e]
    pos2 = (jnp.cumsum(mask2, axis=0) - mask2 +
            jnp.sum(mask1, axis=0, keepdims=True)) * mask2
    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)

    g1 = jnp.sum(probs * keep1, axis=-1)  # [t]
    g2 = jnp.sum(probs * keep2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    routed = jnp.sum(mask1) + jnp.sum(
        mask2 * (probs_wo1.max(-1) > 0)[:, None])
    kept = jnp.sum(keep1) + jnp.sum(keep2)
    drop_fraction = 1.0 - kept / jnp.maximum(routed, 1.0)

    p1 = jnp.sum(pos1 * keep1, axis=-1).astype(jnp.int32)  # [t]
    p2 = jnp.sum(pos2 * keep2, axis=-1).astype(jnp.int32)
    cap1 = jax.nn.one_hot(p1, capacity, dtype=probs.dtype)  # [t, c]
    cap2 = jax.nn.one_hot(p2, capacity, dtype=probs.dtype)
    combine = (
        g1[:, None, None] * keep1[:, :, None] * cap1[:, None, :]
        + g2[:, None, None] * keep2[:, :, None] * cap2[:, None, :]
    )  # [t, e, c]
    dispatch = combine > 0.0
    return combine, dispatch, aux, drop_fraction


def _switch_gating(logits, capacity: int):
    """Switch-transformer top-1 gating."""
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    mask = jax.nn.one_hot(idx, e, dtype=probs.dtype)
    density = jnp.mean(mask, axis=0)
    density_proxy = jnp.mean(probs, axis=0)
    aux = jnp.sum(density * density_proxy) * e
    pos = jnp.cumsum(mask, axis=0) * mask - mask
    keep = mask * (pos < capacity)
    drop_fraction = 1.0 - jnp.sum(keep) / jnp.maximum(jnp.sum(mask), 1.0)
    g = jnp.sum(probs * keep, axis=-1)
    p = jnp.sum(pos * keep, axis=-1).astype(jnp.int32)
    cap = jax.nn.one_hot(p, capacity, dtype=probs.dtype)
    combine = g[:, None, None] * keep[:, :, None] * cap[:, None, :]
    return combine, combine > 0.0, aux, drop_fraction


class ExpertFFN(Layer):
    """Batched expert FFN: weights [E, in, hidden], [E, hidden, in] with
    the expert dim sharded over ``expert_axis``."""

    def __init__(self, num_experts, d_model, d_hidden, expert_axis="ep",
                 activation="gelu", init_std=0.02):
        super().__init__()
        init = I.Normal(0.0, init_std)
        self.w1 = self.create_parameter(
            (num_experts, d_model, d_hidden), default_initializer=init,
            spec=(expert_axis, None, "tp"),
        )
        self.w2 = self.create_parameter(
            (num_experts, d_hidden, d_model), default_initializer=init,
            spec=(expert_axis, "tp", None),
        )
        self.b1 = self.create_parameter(
            (num_experts, d_hidden), is_bias=True, spec=(expert_axis, "tp")
        )
        self.b2 = self.create_parameter(
            (num_experts, d_model), is_bias=True, spec=(expert_axis, None)
        )
        self.act = getattr(F, activation)

    def forward(self, x):
        # x: [E, cap_total, d_model]
        h = jnp.einsum("ecm,emh->ech", x, self.w1.value) + self.b1.value[:, None]
        h = self.act(h)
        return jnp.einsum("ech,ehm->ecm", h, self.w2.value) + self.b2.value[:, None]


class MoELayer(Layer):
    """Parity: incubate MoELayer(gate={...}, experts=[...]).

    forward(x: [batch, seq, d_model]) -> (y, aux_loss). Stores the last
    aux loss in ``self.last_aux_loss`` for trainers that prefer the
    paddle-style side-channel.
    """

    def __init__(
        self,
        d_model: int,
        num_experts: int,
        d_hidden: Optional[int] = None,
        gate: str = "gshard",
        top_k: int = 2,
        capacity_factor: float = 1.25,
        expert_axis: str = "ep",
        aux_loss_weight: float = 1e-2,
    ):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.gate_type = gate
        self.top_k = 1 if gate == "switch" else top_k
        self.capacity_factor = capacity_factor
        self.aux_loss_weight = aux_loss_weight
        self.gate_weight = self.create_parameter(
            (d_model, num_experts),
            default_initializer=I.Normal(0.0, 0.02),
        )
        self.expert_axis = expert_axis
        self.experts = ExpertFFN(
            num_experts, d_model, d_hidden or 4 * d_model, expert_axis
        )
        self.last_aux_loss = 0.0
        self.last_drop_fraction = 0.0  # scalar jnp: routed-but-dropped share

    def capacity(self, tokens: int) -> int:
        cap = int(self.capacity_factor * tokens * self.top_k / self.num_experts)
        return max(cap, 4)

    def forward(self, x):
        b, s, m = x.shape
        tokens = b * s
        xf = x.reshape(tokens, m)
        logits = (xf.astype(jnp.float32) @
                  self.gate_weight.value.astype(jnp.float32))
        cap = self.capacity(tokens)
        if self.gate_type == "switch":
            combine, dispatch, aux, dropped = _switch_gating(logits, cap)
        else:
            combine, dispatch, aux, dropped = _top2_gating(logits, cap)
        combine = combine.astype(x.dtype)
        # dispatch: [t, e, c] x [t, m] -> [e, c, m]; GSPMD inserts the
        # token→expert all-to-all here (expert dim sharded)
        expert_in = jnp.einsum(
            "tec,tm->ecm", dispatch.astype(x.dtype), xf
        )
        expert_in = shard_activation(expert_in, self.expert_axis, None, None)
        expert_out = self.experts(expert_in)
        expert_out = shard_activation(expert_out, self.expert_axis, None, None)
        y = jnp.einsum("tec,ecm->tm", combine, expert_out)
        self.last_aux_loss = aux * self.aux_loss_weight
        self.last_drop_fraction = dropped
        return y.reshape(b, s, m), self.last_aux_loss


def _dropless_topk_gating(logits, top_k: int):
    """Top-k gating with NO capacity clamp: every routed token is
    processed. Returns (expert_idx [t, k], gates [t, k], aux)."""
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    gates, expert_idx = jax.lax.top_k(probs, top_k)
    gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
    # load-balance aux (GShard form on the top-1 assignment)
    mask1 = jax.nn.one_hot(expert_idx[:, 0], e, dtype=probs.dtype)
    aux = jnp.sum(jnp.mean(mask1, 0) * jnp.mean(probs, 0)) * e
    return expert_idx, gates, aux


def dropless_moe_apply(x, expert_idx, gates, w1, b1, w2, b2, act):
    """MegaBlocks-style dropless dispatch, TPU-native form: sort the
    (token, expert) assignments by expert and run ONE grouped matmul per
    projection via ``jax.lax.ragged_dot`` — XLA's grouped-GEMM primitive
    tiles the ragged group dim onto the MXU without materializing
    one-hot dispatch tensors or dropping overflow tokens.

    x: [t, m]; expert_idx/gates: [t, k]; w1: [E, m, h]; w2: [E, h, m].
    Parity: the reference's dropless/"no-token-dropping" MoE modes
    (incubate moe capacity_factor=None paths).
    """
    t, k = expert_idx.shape
    E = w1.shape[0]
    flat_e = expert_idx.reshape(-1)             # [t*k]
    order = jnp.argsort(flat_e)                 # stable
    inv = jnp.argsort(order)
    xs = jnp.repeat(x, k, axis=0)[order]        # [t*k, m] sorted by expert
    group_sizes = jnp.bincount(flat_e, length=E).astype(jnp.int32)
    h = jax.lax.ragged_dot(xs, w1, group_sizes)
    h = h + jnp.repeat(b1, group_sizes, axis=0,
                       total_repeat_length=t * k)
    h = act(h)
    y = jax.lax.ragged_dot(h, w2, group_sizes)
    y = y + jnp.repeat(b2, group_sizes, axis=0,
                       total_repeat_length=t * k)
    y = y[inv].reshape(t, k, -1)                # unsort, [t, k, m]
    return jnp.sum(y * gates[..., None].astype(y.dtype), axis=1)


class DroplessMoELayer(MoELayer):
    """MoELayer with exact (no-drop) routing via grouped matmuls.

    Tradeoff vs the capacity path: no token is ever dropped and no
    [t, e, c] dispatch tensors exist, but the grouped matmul keeps the
    expert weights unsharded along the expert dim (ragged_dot's group
    dim cannot shard under GSPMD), so use the capacity path when
    ep_degree > 1. last_drop_fraction is always 0 here by construction.
    """

    def __init__(self, *args, **kwargs):
        # ragged_dot's group dim cannot shard under GSPMD: expert weights
        # stay REPLICATED (spec None on the expert dim), never "ep" —
        # otherwise every layer call would all-gather the one tensor EP
        # exists to shard. Use the capacity MoELayer for ep_degree > 1.
        kwargs["expert_axis"] = None
        super().__init__(*args, **kwargs)

    def forward(self, x):
        b, s, m = x.shape
        xf = x.reshape(b * s, m)
        logits = (xf.astype(jnp.float32) @
                  self.gate_weight.value.astype(jnp.float32))
        expert_idx, gates, aux = _dropless_topk_gating(logits, self.top_k)
        y = dropless_moe_apply(
            xf, expert_idx, gates,
            self.experts.w1.value, self.experts.b1.value,
            self.experts.w2.value, self.experts.b2.value,
            self.experts.act)
        self.last_aux_loss = aux * self.aux_loss_weight
        self.last_drop_fraction = jnp.zeros(())
        return y.reshape(b, s, m), self.last_aux_loss
