"""Distributed sharded checkpoint with cross-topology reshard-on-load.

Parity: python/paddle/distributed/checkpoint/save_state_dict.py /
load_state_dict.py — each rank writes its local shards plus a global
metadata file recording distribution info; load reassembles slices for a
*different* topology (SURVEY.md §5 "Checkpoint / resume").

TPU-native layout: one directory per checkpoint;
  metadata.json                 — {name: {shape, dtype, chunks:[{offset,
                                   shape, file}]}}
  chunk files (.npy)            — unique shard payloads (replicas deduped
                                   by offset key)
Load path: ``jax.make_array_from_callback`` asks for exactly the slice
each target device needs; the reader assembles it from overlapping saved
chunks — resharding from any source topology to any target topology
without ever materializing full tensors on one host (chunks are read via
np.load mmap).

Multi-host: each process writes only shards it owns (addressable) whose
first-replica device belongs to it; rank 0 merges metadata (single-host
dev boxes write everything directly).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import jax
import numpy as np


def _chunk_filename(name: str, offset) -> str:
    off = "_".join(str(o) for o in offset) if offset else "scalar"
    safe = name.replace("/", "__").replace(".", "_")
    return f"{safe}__{off}.npy"


def save_state_dict(state_dict: Dict[str, jax.Array], path: str) -> None:
    """Save a flat {name: jax.Array} dict (values may be sharded global
    arrays)."""
    os.makedirs(path, exist_ok=True)
    meta = {}
    pid = jax.process_index()
    for name, arr in state_dict.items():
        arr = arr if isinstance(arr, jax.Array) else jax.numpy.asarray(arr)
        entry = {
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "chunks": [],
        }
        seen_offsets = set()
        for shard in arr.addressable_shards:
            idx = shard.index  # tuple of slices into the global shape
            offset = tuple(
                (s.start or 0) for s in idx
            ) if arr.ndim else ()
            if offset in seen_offsets:
                continue  # replica of a chunk we already wrote
            seen_offsets.add(offset)
            # in multi-host, only the process owning the first replica of
            # this chunk writes it
            if shard.replica_id != 0:
                continue
            fname = _chunk_filename(name, offset)
            data = np.asarray(shard.data)
            if str(data.dtype) == "bfloat16":
                # numpy can't serialize ml_dtypes natively; store raw bits
                data = data.view(np.uint16)
            np.save(os.path.join(path, fname), data)
            entry["chunks"].append({
                "offset": list(offset),
                "shape": list(shard.data.shape),
                "file": fname,
            })
        meta[name] = entry
    meta_file = os.path.join(path, f"metadata_{pid}.json")
    with open(meta_file, "w") as f:
        json.dump(meta, f)
    # merge per-process metadata (rank 0; trivially itself single-host)
    if pid == 0:
        merged: Dict = {}
        for fn in sorted(os.listdir(path)):
            if fn.startswith("metadata_") and fn.endswith(".json"):
                with open(os.path.join(path, fn)) as f:
                    part = json.load(f)
                for k, v in part.items():
                    if k not in merged:
                        merged[k] = v
                    else:
                        have = {tuple(c["offset"]) for c in merged[k]["chunks"]}
                        for c in v["chunks"]:
                            if tuple(c["offset"]) not in have:
                                merged[k]["chunks"].append(c)
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(merged, f, indent=1)


class _ChunkReader:
    def __init__(self, path: str, entry: dict):
        self.path = path
        self.entry = entry

    def read_slice(self, index) -> np.ndarray:
        """Assemble global[index] from saved chunks (mmap'd reads)."""
        shape = self.entry["shape"]
        is_bf16 = self.entry["dtype"] == "bfloat16"
        if is_bf16:
            import ml_dtypes

            dtype = np.dtype(ml_dtypes.bfloat16)
        else:
            dtype = np.dtype(self.entry["dtype"])
        starts = [(s.start or 0) for s in index] if shape else []
        stops = [
            (s.stop if s.stop is not None else dim)
            for s, dim in zip(index, shape)
        ]
        out_shape = [b - a for a, b in zip(starts, stops)]
        out = np.zeros(out_shape, dtype)
        for c in self.entry["chunks"]:
            coff, cshape = c["offset"], c["shape"]
            # overlap of [starts, stops) with [coff, coff+cshape)
            lo = [max(a, o) for a, o in zip(starts, coff)]
            hi = [min(b, o + s) for b, o, s in zip(stops, coff, cshape)]
            if any(l >= h for l, h in zip(lo, hi)):
                continue
            data = np.load(os.path.join(self.path, c["file"]),
                           mmap_mode="r", allow_pickle=False)
            src = tuple(
                slice(l - o, h - o) for l, o, h in zip(lo, coff, hi)
            )
            dst = tuple(
                slice(l - a, h - a) for l, a, h in zip(lo, starts, hi)
            )
            piece = np.asarray(data[src])
            if is_bf16:
                piece = piece.view(dtype)
            out[dst] = piece
        return out


def load_state_dict(
    path: str,
    target: Optional[Dict[str, jax.Array]] = None,
    shardings: Optional[Dict] = None,
) -> Dict[str, jax.Array]:
    """Load a checkpoint, resharding to the requested layout.

    ``target``: {name: existing array} — layouts (shardings) are taken
    from it. Or pass ``shardings`` {name: Sharding} directly. With
    neither, arrays load replicated on the default device.
    """
    import jax.numpy as jnp

    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    out = {}
    for name, entry in meta.items():
        reader = _ChunkReader(path, entry)
        shape = tuple(entry["shape"])
        dtype = jnp.dtype(entry["dtype"])
        sharding = None
        if shardings and name in shardings:
            sharding = shardings[name]
        elif target is not None and name in target:
            sharding = target[name].sharding
        if sharding is None:
            full = reader.read_slice(
                tuple(slice(0, s) for s in shape)
            )
            out[name] = jnp.asarray(full).astype(dtype)
        else:
            arr = jax.make_array_from_callback(
                shape, sharding,
                lambda idx, r=reader, dt=dtype: r.read_slice(idx).astype(dt),
            )
            out[name] = arr
    return out


def save_model(model, path: str):
    save_state_dict(dict(model.state_dict()), path)


def load_model(model, path: str):
    params = dict(model.named_parameters())
    shardings = {
        n: p.value.sharding for n, p in params.items()
        if isinstance(p.value, jax.Array)
    }
    loaded = load_state_dict(path, shardings=shardings)
    model.set_state_dict(loaded)
    return model
