"""Distributed sharded checkpoint: atomic, async, cross-topology reshard.

Parity: python/paddle/distributed/checkpoint/save_state_dict.py /
load_state_dict.py — each rank writes its local shards plus a global
metadata file recording distribution info; load reassembles slices for a
*different* topology (SURVEY.md §5 "Checkpoint / resume"). The TPU-world
equivalent of the async save path is orbax/tensorstore-style: snapshot
device→host synchronously (cheap, bounded by HBM→host bandwidth), then
write to disk on a background thread while training continues.

Layout: one directory per checkpoint;
  metadata.json        — {name: {shape, dtype, chunks:[{offset, shape,
                          file}]}}
  chunk files (.npy)   — unique shard payloads (one writer per chunk:
                          the process holding replica 0)
  COMMITTED            — marker written last; its presence means the
                          directory is complete and uncorrupted.

Atomicity: all writers target ``<path>.tmp``; after a cross-process
barrier, rank 0 merges metadata, writes the COMMITTED marker, and
atomically swaps the tmp dir into place (rename old → ``.old``, tmp →
final, delete old). A crash at any point leaves either the previous
intact checkpoint at ``path`` or nothing — never a torn directory that
load would half-read.

Load path: ``jax.make_array_from_callback`` asks for exactly the slice
each target device needs; the reader assembles it from overlapping saved
chunks — resharding from any source topology to any target topology
without ever materializing full tensors on one host (chunks are read via
np.load mmap).
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from typing import Dict, Optional

import jax
import numpy as np

COMMITTED_MARKER = "COMMITTED"


def _chunk_filename(name: str, offset) -> str:
    off = "_".join(str(o) for o in offset) if offset else "scalar"
    safe = name.replace("/", "__").replace(".", "_")
    return f"{safe}__{off}.npy"


def _barrier(tag: str) -> None:
    """Cross-process barrier. No-op single-process; on multi-host uses the
    jax coordination service (the same store launch/elastic rendezvous
    with)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"paddle_tpu.ckpt.{tag}")


def _snapshot_to_host(state_dict: Dict[str, jax.Array]):
    """Device→host copy of every locally-owned unique chunk.

    Returns {name: (shape, dtype_str, [(offset, np.ndarray)])}. This is
    the only part of an async save that blocks training: once it returns,
    the training step may mutate/donate the arrays freely.
    """
    snap = {}
    for name, arr in state_dict.items():
        if not isinstance(arr, jax.Array):
            # host-local leaf (e.g. the TrainStep step counter): every
            # process holds an identical copy with no replica topology,
            # so only rank 0 may write it — otherwise all ranks race on
            # the same chunk file
            arr = np.asarray(arr)
            chunks = ([(tuple(0 for _ in arr.shape), arr)]
                      if jax.process_index() == 0 else [])
            snap[name] = (list(arr.shape), str(arr.dtype), chunks)
            continue
        chunks = []
        seen_offsets = set()
        for shard in arr.addressable_shards:
            # Only the process holding replica 0 of a chunk writes it —
            # this skip must happen BEFORE the offset dedup, otherwise a
            # non-zero replica enumerating first poisons seen_offsets and
            # the real writer's chunk is silently dropped.
            if shard.replica_id != 0:
                continue
            idx = shard.index  # tuple of slices into the global shape
            offset = tuple((s.start or 0) for s in idx) if arr.ndim else ()
            if offset in seen_offsets:
                continue
            seen_offsets.add(offset)
            chunks.append((offset, np.asarray(shard.data)))
        snap[name] = (list(arr.shape), str(arr.dtype), chunks)
    return snap


def _npy_header(arr: np.ndarray) -> bytes:
    """The .npy v1 header bytes np.save would write for ``arr``."""
    import io as _io

    buf = _io.BytesIO()
    np.lib.format.write_array_header_1_0(
        buf, np.lib.format.header_data_from_array_1_0(arr))
    return buf.getvalue()


def _native_write_chunks(files) -> bool:
    """Write [(path, np.ndarray)] via the C thread-pool writer
    (csrc/ckptio.cpp — parity: the reference's C++ save executors).
    Returns False when the library is unavailable (caller falls back)."""
    try:
        from ..io.native import load_ckpt_writer

        lib = load_ckpt_writer()
    except Exception:
        return False
    n = len(files)
    if n == 0:
        return True
    import ctypes

    arrays = [np.ascontiguousarray(a) for _, a in files]
    headers = [_npy_header(a) for a in arrays]
    c_paths = (ctypes.c_char_p * n)(
        *[p.encode() for p, _ in files])
    c_headers = (ctypes.POINTER(ctypes.c_uint8) * n)(
        *[ctypes.cast(ctypes.c_char_p(h),
                      ctypes.POINTER(ctypes.c_uint8)) for h in headers])
    c_hlens = (ctypes.c_int64 * n)(*[len(h) for h in headers])
    c_datas = (ctypes.POINTER(ctypes.c_uint8) * n)(
        *[ctypes.cast(a.ctypes.data, ctypes.POINTER(ctypes.c_uint8))
          for a in arrays])
    c_dlens = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
    failures = lib.ptck_write_batch(
        n, c_paths, c_headers, c_hlens, c_datas, c_dlens,
        min(n, 8), 1)  # fsync: data durable before COMMITTED can land
    if failures:
        raise OSError(f"native checkpoint writer: {failures}/{n} "
                      f"chunk files failed to write")
    return True


def _write_snapshot(snap, tmp_path: str) -> None:
    """Disk phase of a save: write chunk files + this process's metadata
    part into the (already-created) tmp dir. Chunk files go through the
    native parallel writer when available (np.save loop as fallback)."""
    meta = {}
    files = []
    pid = jax.process_index()
    for name, (shape, dtype, chunks) in snap.items():
        entry = {"shape": shape, "dtype": dtype, "chunks": []}
        for offset, data in chunks:
            fname = _chunk_filename(name, offset)
            if str(data.dtype) == "bfloat16":
                # numpy can't serialize ml_dtypes natively; store raw bits
                data = data.view(np.uint16)
            files.append((os.path.join(tmp_path, fname), data))
            entry["chunks"].append({
                "offset": list(offset),
                "shape": list(data.shape),
                "file": fname,
            })
        meta[name] = entry
    if not _native_write_chunks(files):
        for path_i, data in files:
            np.save(path_i, data)
    # temp-write + rename so a concurrent reader (the async commit poll
    # counts metadata parts by listdir) never sees a partial file
    part = os.path.join(tmp_path, f"metadata_{pid}.json")
    with open(part + ".part", "w") as f:
        json.dump(meta, f)
    os.replace(part + ".part", part)


def _merge_metadata(tmp_path: str) -> None:
    merged: Dict = {}
    for fn in sorted(os.listdir(tmp_path)):
        if fn.startswith("metadata_") and fn.endswith(".json"):
            with open(os.path.join(tmp_path, fn)) as f:
                part = json.load(f)
            for k, v in part.items():
                if k not in merged:
                    merged[k] = v
                else:
                    have = {tuple(c["offset"]) for c in merged[k]["chunks"]}
                    for c in v["chunks"]:
                        if tuple(c["offset"]) not in have:
                            merged[k]["chunks"].append(c)
    with open(os.path.join(tmp_path, "metadata.json"), "w") as f:
        json.dump(merged, f, indent=1)


def _commit(tmp_path: str, path: str) -> None:
    """Marker + atomic swap. Runs on rank 0 only.

    POSIX cannot atomically swap two directories, so there is a crash
    window between the two renames where ``path`` is absent and the
    previous checkpoint sits at ``path + ".old"`` — ``_recover`` (called
    by every save and load) rolls that state back to the previous intact
    checkpoint."""
    with open(os.path.join(tmp_path, COMMITTED_MARKER), "w") as f:
        f.write("1")
    old = path + ".old"
    if os.path.isdir(old):
        shutil.rmtree(old)
    if os.path.isdir(path):
        os.rename(path, old)
    os.rename(tmp_path, path)
    if os.path.isdir(old):
        shutil.rmtree(old)


def _recover(path: str) -> None:
    """Heal a crash between _commit's two renames. Rank-0-only (every
    rank healing at once would race the rename; and on a live job only
    rank 0 ever commits, so only it may roll state forward/back).

    Two cases, checked in order:
    - ``path`` missing but ``path.tmp`` carries the COMMITTED marker:
      the crash hit AFTER the marker write — finish the commit by
      promoting tmp (this also means a *concurrently running* _commit
      between its renames is indistinguishable; promoting tmp yields
      the same final state that commit was about to produce).
    - ``path`` missing but ``path.old`` exists: the new checkpoint never
      made it — restore the previous one.
    """
    if jax.process_index() != 0 or os.path.isdir(path):
        return
    tmp, old = path + ".tmp", path + ".old"
    if os.path.isfile(os.path.join(tmp, COMMITTED_MARKER)):
        os.rename(tmp, path)
        if os.path.isdir(old):
            shutil.rmtree(old)
    elif os.path.isdir(old):
        os.rename(old, path)


_NEST_SEP = "//"
_EMPTY_DICT_LEAF = "__empty_dict__"


def _flatten_nested(d, prefix="", keep_empty=True):
    """Flatten nested dicts to {"a//b//c": leaf}. Leaf = anything that is
    not a dict; scalars (the TrainStep step counter) become 0-d arrays at
    snapshot time. ``//`` cannot collide with parameter names (paddle
    names use ``.``; module paths never contain ``//``). Empty subtrees
    (SGD's slot dicts, an fp32 model's master dict) are kept via a
    marker leaf so the restored pytree structure matches exactly —
    ``keep_empty=False`` on lookup-only flattens (load target/shardings),
    where a synthesized marker array would be mistaken for a Sharding."""
    flat = {}
    for k, v in d.items():
        key = f"{prefix}{_NEST_SEP}{k}" if prefix else str(k)
        if isinstance(v, dict):
            if v:
                flat.update(_flatten_nested(v, key, keep_empty))
            elif keep_empty:
                flat[f"{key}{_NEST_SEP}{_EMPTY_DICT_LEAF}"] = np.zeros(
                    (), np.int8)
        else:
            flat[key] = v
    return flat


def _unflatten_nested(flat):
    out = {}
    for key, v in flat.items():
        parts = key.split(_NEST_SEP)
        cur = out
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        if parts[-1] != _EMPTY_DICT_LEAF:
            cur[parts[-1]] = v
    return out


def save_state_dict(state_dict: Dict[str, jax.Array], path: str) -> None:
    """Atomically save a {name: jax.Array} dict (values may be sharded
    global arrays; nested dicts — e.g. a whole TrainStep.state_dict() —
    are flattened transparently). Blocks until the checkpoint is
    committed."""
    snap = _snapshot_to_host(_flatten_nested(state_dict))
    tmp_path = path + ".tmp"
    if jax.process_index() == 0:
        _recover(path)
        if os.path.isdir(tmp_path):  # leftover from a crashed save
            shutil.rmtree(tmp_path)
        os.makedirs(tmp_path, exist_ok=True)
    _barrier("tmpdir")
    _write_snapshot(snap, tmp_path)
    _barrier("written")
    if jax.process_index() == 0:
        _merge_metadata(tmp_path)
        _commit(tmp_path, path)
    _barrier("committed")


class AsyncCheckpointer:
    """Orbax-style async saver: ``save()`` blocks only for the
    device→host snapshot, then the serialize+commit runs on a background
    thread. At most one save is in flight; a new ``save`` waits for the
    previous one (so checkpoints can never commit out of order).

    Usage::

        saver = AsyncCheckpointer()
        saver.save(state, "/ckpt/step_100")   # returns immediately
        ... keep training ...
        saver.wait_until_finished()           # before exit / next save
    """

    def __init__(self, commit_timeout: float = 600.0):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self.commit_timeout = commit_timeout

    def save(self, state_dict: Dict[str, jax.Array], path: str) -> None:
        self.wait_until_finished()
        # the snapshot is the only blocking part
        snap = _snapshot_to_host(_flatten_nested(state_dict))
        tmp_path = path + ".tmp"
        if jax.process_index() == 0:
            _recover(path)
            if os.path.isdir(tmp_path):
                shutil.rmtree(tmp_path)
            os.makedirs(tmp_path, exist_ok=True)
        _barrier("async.tmpdir")

        def _worker():
            try:
                _write_snapshot(snap, tmp_path)
                # NOTE: no cross-process barrier inside the worker thread
                # (the coordination service is not thread-safe to call
                # concurrently with the training step's collectives).
                # Multi-host async commit instead counts metadata parts:
                # rank 0 commits once all N parts exist.
                if jax.process_index() == 0:
                    import time

                    want = jax.process_count()
                    deadline = time.monotonic() + self.commit_timeout
                    while True:
                        have = len([
                            fn for fn in os.listdir(tmp_path)
                            if fn.startswith("metadata_")
                            and fn.endswith(".json")
                        ])
                        if have >= want:
                            break
                        if time.monotonic() > deadline:
                            raise TimeoutError(
                                f"async checkpoint commit: only {have}/"
                                f"{want} ranks wrote metadata within "
                                f"{self.commit_timeout}s (peer died "
                                f"mid-save?); leaving {tmp_path} "
                                f"uncommitted")
                        time.sleep(0.05)
                    _merge_metadata(tmp_path)
                    _commit(tmp_path, path)
            except BaseException as e:  # surfaced on next wait/save
                self._error = e

        self._thread = threading.Thread(target=_worker, daemon=True)
        self._thread.start()

    def wait_until_finished(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err


def is_committed(path: str) -> bool:
    """True iff ``path`` is a complete, uncorrupted checkpoint dir
    (restoring it first from ``.old`` if a commit crashed mid-swap)."""
    _recover(path)
    return os.path.isfile(os.path.join(path, COMMITTED_MARKER)) or (
        # pre-marker checkpoints (round ≤2 layout) are considered
        # committed when merged metadata exists
        os.path.isfile(os.path.join(path, "metadata.json"))
    )


class _ChunkReader:
    def __init__(self, path: str, entry: dict):
        self.path = path
        self.entry = entry

    def read_slice(self, index) -> np.ndarray:
        """Assemble global[index] from saved chunks (mmap'd reads)."""
        shape = self.entry["shape"]
        is_bf16 = self.entry["dtype"] == "bfloat16"
        if is_bf16:
            import ml_dtypes

            dtype = np.dtype(ml_dtypes.bfloat16)
        else:
            dtype = np.dtype(self.entry["dtype"])
        starts = [(s.start or 0) for s in index] if shape else []
        stops = [
            (s.stop if s.stop is not None else dim)
            for s, dim in zip(index, shape)
        ]
        out_shape = [b - a for a, b in zip(starts, stops)]
        out = np.zeros(out_shape, dtype)
        for c in self.entry["chunks"]:
            coff, cshape = c["offset"], c["shape"]
            # overlap of [starts, stops) with [coff, coff+cshape)
            lo = [max(a, o) for a, o in zip(starts, coff)]
            hi = [min(b, o + s) for b, o, s in zip(stops, coff, cshape)]
            if any(l >= h for l, h in zip(lo, hi)):
                continue
            data = np.load(os.path.join(self.path, c["file"]),
                           mmap_mode="r", allow_pickle=False)
            src = tuple(
                slice(l - o, h - o) for l, o, h in zip(lo, coff, hi)
            )
            dst = tuple(
                slice(l - a, h - a) for l, a, h in zip(lo, starts, hi)
            )
            piece = np.asarray(data[src])
            if is_bf16:
                piece = piece.view(dtype)
            out[dst] = piece
        return out


def load_state_dict(
    path: str,
    target: Optional[Dict[str, jax.Array]] = None,
    shardings: Optional[Dict] = None,
) -> Dict[str, jax.Array]:
    """Load a checkpoint, resharding to the requested layout.

    ``target``: {name: existing array} — layouts (shardings) are taken
    from it. Or pass ``shardings`` {name: Sharding} directly. With
    neither, arrays load replicated on the default device. Nested dicts
    (saved from e.g. TrainStep.state_dict()) round-trip: target/shardings
    may be nested the same way, and the result is re-nested.
    """
    import jax.numpy as jnp

    if target is not None:
        target = _flatten_nested(target, keep_empty=False)
    if shardings is not None:
        shardings = _flatten_nested(shardings, keep_empty=False)

    # is_committed lets rank 0 heal any crashed-commit state; the
    # barrier keeps the other ranks from racing the rename on a shared
    # filesystem before they check the marker themselves
    if jax.process_index() == 0:
        is_committed(path)  # triggers _recover on rank 0
    _barrier("load.recover")
    if not is_committed(path):
        raise FileNotFoundError(
            f"{path!r} is not a committed checkpoint (no "
            f"{COMMITTED_MARKER} marker / metadata.json — crashed save?)"
        )
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    out = {}
    for name, entry in meta.items():
        reader = _ChunkReader(path, entry)
        shape = tuple(entry["shape"])
        dtype = jnp.dtype(entry["dtype"])
        sharding = None
        if shardings and name in shardings:
            sharding = shardings[name]
        elif target is not None and name in target:
            # scalar leaves (the step counter) have no sharding
            sharding = getattr(target[name], "sharding", None)
        if sharding is None:
            full = reader.read_slice(
                tuple(slice(0, s) for s in shape)
            )
            out[name] = jnp.asarray(full).astype(dtype)
        else:
            arr = jax.make_array_from_callback(
                shape, sharding,
                lambda idx, r=reader, dt=dtype: r.read_slice(idx).astype(dt),
            )
            out[name] = arr
    if any(_NEST_SEP in name for name in out):
        return _unflatten_nested(out)
    return out


def save_model(model, path: str):
    save_state_dict(dict(model.state_dict()), path)


def load_model(model, path: str):
    params = dict(model.named_parameters())
    shardings = {
        n: p.value.sharding for n, p in params.items()
        if isinstance(p.value, jax.Array)
    }
    loaded = load_state_dict(path, shardings=shardings)
    model.set_state_dict(loaded)
    return model
