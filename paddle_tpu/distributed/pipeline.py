"""Pipeline parallelism.

Parity: fleet/meta_parallel/pipeline_parallel.py (``PipelineParallel``
1F1B / F-then-B schedules), pp_layers.py (``PipelineLayer`` /
``LayerDesc`` segmentation), pp_utils/p2p_communication.py (send/recv
with shape-header protocol), and the C++ FleetExecutor actor runtime that
orchestrates static PP (paddle/fluid/distributed/fleet_executor/).

TPU-native design: a *single SPMD program*. Stage parameters are stacked
on a leading [pp] dim sharded over the "pp" mesh axis; microbatches march
through stages with ``jax.lax.ppermute`` rotations inside a
``shard_map`` over the pp axis only (tp/fsdp/sep stay with GSPMD via
auto axes). The schedule emerges from one scanned loop of
``n_micro + pp - 1`` ticks (the classic pipeline diagonal); autodiff
through the shard_map yields the reverse-rotation backward, and XLA's
scheduler overlaps the ppermute with stage compute — the job of the
reference's p2p streams + interceptor actors. 1F1B's memory profile is
recovered with ``jax.checkpoint`` around the stage body (stash only
boundary activations).

There is no p2p protocol code because activations never leave the
compiled program.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..core import initializer as I
from ..core.module import Layer


def pipeline_apply(
    stage_fn: Callable,
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    n_micro: int,
    axis: str = "pp",
    remat: bool = True,
):
    """Run ``y = stage_{pp-1}(...stage_0(x))`` pipelined over microbatches.

    stage_fn(params_slice, x_mb) -> y_mb — one stage's compute; activations
    must keep the same shape/dtype across stages (transformer trunk).
    stage_params: pytree whose leaves have leading dim pp (sharded P("pp")).
    x: [n_micro, mb, ...] microbatched input (replicated over pp).
    """
    pp = mesh.shape[axis]
    total_ticks = n_micro + pp - 1

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)

    def per_stage(params, xs):
        # inside shard_map: params leaves have leading dim 1 (this stage's
        # slice); xs: [n_micro, mb, ...] (full copy on every stage)
        stage = jax.lax.axis_index(axis)
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        mb_shape = xs.shape[1:]

        def tick(carry, t):
            buf = carry  # activation arriving at this stage this tick
            # stage 0 ingests microbatch t (if in range); others take buf
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            inp = jnp.where(
                stage == 0,
                jax.lax.dynamic_index_in_dim(xs, mb_idx, 0, keepdims=False),
                buf,
            )
            out = body(my_params, inp)
            # rotate stage i → i+1 (last stage's output falls off the ring)
            nxt = jax.lax.ppermute(
                out, axis, [(i, i + 1) for i in range(pp - 1)]
            )
            # last stage emits its result at ticks [pp-1, total)
            emit = jnp.where(
                stage == pp - 1,
                out,
                jnp.zeros_like(out),
            )
            return nxt, emit

        # mark the carry as pp-varying so scan's carry types line up with
        # the ppermute output
        init = jax.lax.pcast(
            jnp.zeros((*mb_shape,), xs.dtype), axis, to="varying"
        )
        _, emits = jax.lax.scan(
            tick, init, jnp.arange(total_ticks)
        )  # emits: [total_ticks, mb, ...] (nonzero only on last stage)
        # keep the last n_micro ticks' outputs; psum broadcasts the last
        # stage's results (all other stages emitted zeros)
        ys = emits[pp - 1:]
        ys = jax.lax.psum(ys, axis) if pp > 1 else ys
        return ys

    spec_params = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    fn = shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(spec_params, P()),
        # with check_vma off a replicated out_spec can't be proven, so the
        # (identical) per-stage results stack on a leading pp dim and the
        # first block is taken outside
        out_specs=P(axis),
        axis_names={axis},
    )
    ys = fn(stage_params, x)
    return ys[:n_micro]


class LayerDesc:
    """Parity: fleet LayerDesc — a deferred layer constructor."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Parity: tied weights across stages (e.g. embedding/lm-head). In the
    SPMD pipeline tied weights live outside the pipelined trunk, so this
    marks layers the segmenter must keep out of the stage stack."""

    def __init__(self, key, layer_cls, *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key


class PipelineLayer(Layer):
    """Parity: fleet PipelineLayer — segments a homogeneous trunk of
    LayerDescs into pp stages with layers_per_stage chunks each.

    TPU-native storage: ONE prototype layer defines the per-layer pytree;
    parameters for all L layers are stacked on a leading [L] dim
    (spec ("pp",) + the prototype's own spec shifted right), giving XLA
    the stacked layout pipeline_apply needs with zero copying.

    forward(x, n_micro) runs the pipelined trunk when a mesh with pp>1 is
    active, else a plain sequential scan (identical numerics).
    """

    def __init__(self, layer_desc: LayerDesc, num_layers: int,
                 num_stages: Optional[int] = None, seg_method="uniform"):
        super().__init__()
        self.num_layers = num_layers
        self.num_stages = num_stages
        self.prototype = layer_desc.build()
        # stack per-layer params: [L, *shape]
        protos = list(self.prototype.named_parameters())
        import numpy as np

        from ..core import random as random_mod
        from ..core.parameter import Parameter

        self._stacked_names = []
        for name, p in protos:
            init = p.init_fn or I.XavierNormal()
            vals = [p.value]
            for _ in range(num_layers - 1):
                key = random_mod.next_rng_key("params")
                vals.append(init(key, p.shape, p.dtype))
            stacked = jnp.stack(vals, axis=0)
            spec = ("pp",) + tuple(
                p.spec if p.spec is not None else [None] * p.ndim
            )
            flat = name.replace(".", "__")
            self.add_parameter(
                flat, Parameter(stacked, name=flat, spec=spec)
            )
            self._stacked_names.append((flat, name))

    def stage_params(self):
        return {flat: self._parameters[flat].value
                for flat, _ in self._stacked_names}

    def _apply_one(self, layer_params, x):
        """Run the prototype with one layer's params bound."""
        from ..core.functional import bind_params

        unflat = {orig: layer_params[flat]
                  for flat, orig in self._stacked_names}
        with bind_params(self.prototype, unflat):
            return self.prototype(x)

    def forward(self, x, n_micro: int = 1, mesh: Optional[Mesh] = None):
        from .sharding import current_mesh

        mesh = mesh or current_mesh()
        params = self.stage_params()
        pp = mesh.shape.get("pp", 1) if mesh is not None else 1
        if mesh is not None and pp > 1:
            assert self.num_layers % pp == 0, (
                "num_layers must divide evenly into pp stages"
            )
            per_stage = self.num_layers // pp

            def stage_fn(stage_params, mb):
                # stage_params leaves: [per_stage, ...]
                def one(h, layer_params):
                    return self._apply_one(layer_params, h), None

                h, _ = jax.lax.scan(
                    lambda h, lp: one(h, lp), mb, stage_params
                )
                return h

            # reshape leading dim [L] -> [pp, per_stage] then feed pp dim
            stacked = {
                k: v.reshape(pp, per_stage, *v.shape[1:])
                for k, v in params.items()
            }
            if x.shape[0] % n_micro == 0:
                mbs = x.reshape(n_micro, x.shape[0] // n_micro, *x.shape[1:])
            else:
                raise ValueError("batch not divisible by n_micro")
            ys = pipeline_apply(
                stage_fn, stacked, mbs, mesh=mesh, n_micro=n_micro
            )
            return ys.reshape(x.shape[0], *ys.shape[2:])
        # sequential fallback — same math, no pipeline
        def one(h, layer_params):
            return self._apply_one(layer_params, h), None

        h, _ = jax.lax.scan(one, x, params)
        return h
