"""Pipeline parallelism.

Parity: fleet/meta_parallel/pipeline_parallel.py (``PipelineParallel``
1F1B / F-then-B schedules), pp_layers.py (``PipelineLayer`` /
``LayerDesc`` segmentation + seg_method cost balancing),
pp_utils/p2p_communication.py (send/recv with shape-header protocol),
and the C++ FleetExecutor actor runtime that orchestrates static PP
(paddle/fluid/distributed/fleet_executor/).

TPU-native design: a *single SPMD program*. Stage parameters are stacked
on a leading [pp] dim sharded over the "pp" mesh axis; microbatches march
through stages with ``jax.lax.ppermute`` rotations inside a
``shard_map`` over the pp axis only (tp/fsdp/sep stay with GSPMD via
auto axes). There is no p2p protocol code because activations never
leave the compiled program.

Two schedules, selected by ``strategy.pipeline_configs.schedule_mode``:

- **F-then-B** (GPipe): ``pipeline_apply`` — one scanned loop of
  ``n_micro + pp - 1`` ticks; autodiff through the shard_map yields the
  reverse-rotation backward. Residual memory ∝ n_micro (each stage
  stashes every microbatch's boundary activation for the global backward
  phase), mitigated by ``jax.checkpoint``.
- **1F1B** (+interleaved VPP): ``pipeline_1f1b_step`` — forward AND
  backward live inside one scanned loop of paired F/B ticks, so a
  microbatch's backward starts as soon as its forward leaves the last
  (virtual) stage. Residuals (stage inputs; internals are recomputed at
  backward, the reference's remat policy) live in a ring buffer of
  2·(V−1−v) slots per virtual stage — peak activation memory ∝ pp·vpp,
  INDEPENDENT of n_micro, the property that lets gradient accumulation
  scale. The schedule: F of virtual stage v, microbatch f fires at pair
  tick v+f; B of (v, b) at pair tick 2(V−1)−v+b — the lockstep-SPMD form
  of the reference's 1F1B steady state (fleet pipeline_parallel.py).
  VPP: V = vpp·pp virtual stages placed round-robin (virtual stage v on
  device v mod pp — Megatron/fleet interleaved placement), activations
  lap the ring vpp times; each device holds vpp param chunks and runs
  one F and one B chunk-unit per lap per tick.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from ..jax_compat import pcast as _pcast
from ..jax_compat import shard_map
from ..jax_compat import vma_of as _vma_of
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import initializer as I
from ..core.module import Layer


def pipeline_apply(
    stage_fn: Callable,
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    n_micro: int,
    axis: str = "pp",
    remat: bool = True,
):
    """Run ``y = stage_{pp-1}(...stage_0(x))`` pipelined over microbatches.

    stage_fn(params_slice, x_mb) -> y_mb — one stage's compute; activations
    must keep the same shape/dtype across stages (transformer trunk).
    stage_params: pytree whose leaves have leading dim pp (sharded P("pp")).
    x: [n_micro, mb, ...] microbatched input (replicated over pp).
    """
    pp = mesh.shape[axis]
    total_ticks = n_micro + pp - 1

    body = stage_fn
    if remat:
        body = jax.checkpoint(stage_fn)

    def per_stage(params, xs):
        # inside shard_map: params leaves have leading dim 1 (this stage's
        # slice); xs: [1, n_micro, mb, ...] — real data only on stage 0
        # (other stages' blocks are the zero padding added below), so the
        # input is never all-gathered/replicated across pp
        stage = jax.lax.axis_index(axis)
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        xs = xs[0]
        mb_shape = xs.shape[1:]

        def tick(carry, t):
            buf = carry  # activation arriving at this stage this tick
            # stage 0 ingests microbatch t (if in range); others take buf
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            inp = jnp.where(
                stage == 0,
                jax.lax.dynamic_index_in_dim(xs, mb_idx, 0, keepdims=False),
                buf,
            )
            out = body(my_params, inp)
            # rotate stage i → i+1 (last stage's output falls off the ring)
            nxt = jax.lax.ppermute(
                out, axis, [(i, i + 1) for i in range(pp - 1)]
            )
            # last stage emits its result at ticks [pp-1, total)
            emit = jnp.where(
                stage == pp - 1,
                out,
                jnp.zeros_like(out),
            )
            return nxt, emit

        # mark the carry as pp-varying so scan's carry types line up with
        # the ppermute output
        init = _pcast(
            jnp.zeros((*mb_shape,), xs.dtype), axis, to="varying"
        )
        _, emits = jax.lax.scan(
            tick, init, jnp.arange(total_ticks)
        )  # emits: [total_ticks, mb, ...] (nonzero only on last stage)
        # keep the last n_micro ticks' outputs. No psum: only the last
        # stage's block is real, and the caller slices exactly that block
        # out of the pp-stacked output — zero broadcast traffic.
        return emits[pp - 1:]

    spec_params = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    # stage-0-only input: block 0 is the real data, blocks 1..pp-1 are
    # zeros that only exist to give shard_map a pp-divisible leading dim
    # (each non-0 stage receives a zero block, not a replica)
    xs_blocks = jnp.concatenate(
        [x[None], jnp.zeros((pp - 1, *x.shape), x.dtype)], axis=0
    )
    fn = shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(spec_params, P(axis)),
        out_specs=P(axis),
        axis_names={axis},
    )
    ys = fn(stage_params, xs_blocks)  # [pp * n_micro, mb, ...] stacked
    return ys[(pp - 1) * n_micro:]


# ---------------------------------------------------------------------------
# 1F1B (+ interleaved VPP) — forward and backward in one scanned schedule
# ---------------------------------------------------------------------------
def pipeline_1f1b_step(
    first_fn: Callable,
    stage_fn: Callable,
    last_fn: Callable,
    first_params: Any,
    stage_params: Any,
    last_params: Any,
    x_mbs: Any,
    aux_mbs: Any,
    *,
    mesh: Mesh,
    axis: str = "pp",
    vpp: int = 1,
):
    """One pipelined loss+grad evaluation under the 1F1B schedule.

    - ``first_fn(first_params, x_mb) -> h`` — stage-0 prologue (embedding);
      raw per-microbatch inputs (token ids) are replicated over pp (cheap:
      they are int ids, ~1000x smaller than activations — activations
      themselves never replicate).
    - ``stage_fn(chunk_params, h) -> h`` — one VIRTUAL stage (chunk) of the
      trunk; activations keep shape/dtype across chunks.
    - ``last_fn(last_params, y_mb, aux_mb) -> scalar`` — head + loss
      (mean over the microbatch), evaluated on the last stage the tick a
      microbatch's forward completes; its dy feeds backward immediately.
    - ``stage_params``: pytree with leading dim V = vpp*pp (virtual-stage
      order). Virtual stage v lives on device ``v % pp`` (interleaved
      round-robin — Megatron/fleet VPP placement), so each device holds
      ``vpp`` chunks.
    - ``x_mbs``/``aux_mbs``: pytrees with leading dim n_micro.

    Returns ``(loss_mean, dfirst, dstage, dlast)`` where grads are summed
    over microbatches (divide by n_micro for the mean-loss convention —
    done here so the result matches grad-of-mean).

    Memory: each virtual stage v keeps a ring of 2(V−1−v)+1 saved stage
    INPUTS (internals recomputed at backward); peak ∝ pp·vpp,
    independent of n_micro — the 1F1B property. Schedule (pair tick τ):
    F(v, f) at τ = v + f; B(v, b) at τ = 2(V−1) − v + b. Dependencies:
    F(v−1, f) at τ−1; B(v+1, b) at τ−1; B(V−1, b) in the same tick as
    F(V−1, b).
    """
    pp = mesh.shape[axis]
    leaves = jax.tree_util.tree_leaves(stage_params)
    V = leaves[0].shape[0] if leaves else pp * vpp
    if V != pp * vpp:
        raise ValueError(
            f"stage_params leading dim {V} != pp*vpp = {pp}*{vpp}")
    n_micro = jax.tree_util.tree_leaves(x_mbs)[0].shape[0]
    T = n_micro + 2 * (V - 1)
    R = max(2 * V, 1)  # residual ring slots (≥ max in-flight 2(V-1)+1)

    # virtual-stage order [V, ...] -> device-major [pp, vpp, ...]
    dev_major = jax.tree_util.tree_map(
        lambda p: p.reshape(vpp, pp, *p.shape[1:]).swapaxes(0, 1),
        stage_params,
    )

    x0 = jax.tree_util.tree_map(lambda a: a[0], x_mbs)
    h_sds = jax.eval_shape(first_fn, first_params, x0)

    def per_device(sp, fp, lp, xs, auxs):
        s_idx = jax.lax.axis_index(axis)
        # fp/lp arrive pp-invariant; vjp of an invariant input against a
        # varying output would insert an implicit psum over pp, polluting
        # each device's cotangent with every OTHER device's (masked-out)
        # phantom contribution. Cast to varying so cotangents stay
        # per-device; the caller slices the real device's block.
        fp = jax.tree_util.tree_map(
            lambda p: _pcast(p, (axis,), to="varying"), fp)
        lp = jax.tree_util.tree_map(
            lambda p: _pcast(p, (axis,), to="varying"), lp)
        chunks = jax.tree_util.tree_map(lambda p: p[0], sp)  # [vpp, ...]

        def chunk_params(c):
            return jax.tree_util.tree_map(lambda p: p[c], chunks)

        def vary(x):
            # scan carries become pp-varying through the ppermute/axis_index
            # data flow; the zero-init must carry the same vma type.
            # Idempotent: already-varying values pass through.
            if axis in _vma_of(x):
                return x
            return _pcast(x, (axis,), to="varying")

        zero_h = vary(jnp.zeros(h_sds.shape, h_sds.dtype))
        carry0 = {
            "fbuf": [zero_h for _ in range(vpp)],
            "bbuf": [zero_h for _ in range(vpp)],
            "res": [vary(jnp.zeros((R, *h_sds.shape), h_sds.dtype))
                    for _ in range(vpp)],
            "dstage": [jax.tree_util.tree_map(jnp.zeros_like, chunk_params(c))
                       for c in range(vpp)],
            "dfirst": jax.tree_util.tree_map(
                lambda p: vary(jnp.zeros_like(p)), fp),
            "dlast": jax.tree_util.tree_map(
                lambda p: vary(jnp.zeros_like(p)), lp),
            "loss_sum": vary(jnp.zeros((), jnp.float32)),
        }

        def take_mb(tree, i):
            return jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, i, 0,
                                                       keepdims=False),
                tree,
            )

        def macc(acc, g, active):
            return jax.tree_util.tree_map(
                lambda a, b: a + jnp.where(active, b, 0).astype(a.dtype),
                acc, g,
            )

        def tick(carry, t):
            fbuf, bbuf = carry["fbuf"], carry["bbuf"]
            res, dstage = carry["res"], carry["dstage"]
            dfirst, dlast = carry["dfirst"], carry["dlast"]
            loss_sum = carry["loss_sum"]

            # embedding for the microbatch entering v=0 this tick
            f0 = jnp.clip(t, 0, n_micro - 1)
            a_embed = first_fn(fp, take_mb(xs, f0))

            f_out = [None] * vpp
            b_out = [None] * vpp
            dy_stash = zero_h
            new_fbuf, new_bbuf, new_res = list(fbuf), list(bbuf), list(res)
            new_dstage = list(dstage)

            for c in range(vpp):
                v = c * pp + s_idx  # traced (device-dependent)
                params_c = chunk_params(c)

                # ---- F slot ----
                f = t - v
                active_f = (f >= 0) & (f < n_micro)
                fsafe = jnp.clip(f, 0, n_micro - 1)
                a_in = jnp.where(v == 0, a_embed, fbuf[c])
                slot_f = fsafe % R
                new_res[c] = jnp.where(
                    active_f,
                    jax.lax.dynamic_update_index_in_dim(
                        new_res[c], a_in, slot_f, 0),
                    new_res[c],
                )
                out_f = stage_fn(params_c, a_in)
                f_out[c] = out_f

                # last virtual stage: head+loss now; dy feeds B this tick.
                # v == V-1 requires c == vpp-1 (v = c*pp + s, s < pp), so
                # the head forward+VJP — the vocab-size matmul, usually
                # the most expensive per-tick op — is built ONLY for the
                # final lap, not masked-out for every lap.
                if c == vpp - 1:
                    is_last_v = v == V - 1
                    aux_f = take_mb(auxs, fsafe)
                    loss_f, head_vjp = jax.vjp(
                        lambda lp_, y_: last_fn(lp_, y_, aux_f), lp, out_f)
                    ct_one = _pcast(jnp.ones((), loss_f.dtype),
                                           (axis,), to="varying")
                    dlast_f, dy_f = head_vjp(ct_one)
                    keep = active_f & is_last_v
                    loss_sum = loss_sum + jnp.where(
                        keep, loss_f, 0.0).astype(jnp.float32)
                    dlast = macc(dlast, dlast_f, keep)
                    dy_stash = jnp.where(is_last_v, dy_f, dy_stash)

                # ---- B slot ----
                b = t - (2 * (V - 1) - v)
                active_b = (b >= 0) & (b < n_micro)
                bsafe = jnp.clip(b, 0, n_micro - 1)
                # dy feeds B only where v can be V-1 (the final lap)
                ct_in = (jnp.where(v == V - 1, dy_stash, bbuf[c])
                         if c == vpp - 1 else bbuf[c])
                a_saved = jax.lax.dynamic_index_in_dim(
                    new_res[c], bsafe % R, 0, keepdims=False)
                _, stage_vjp = jax.vjp(stage_fn, params_c, a_saved)
                dp_c, da = stage_vjp(ct_in)
                new_dstage[c] = macc(new_dstage[c], dp_c, active_b)

                # v == 0 (only possible on lap 0): backprop through the
                # prologue (embedding scatter-grad built once, not per lap)
                if c == 0:
                    _, first_vjp = jax.vjp(first_fn, fp, take_mb(xs, bsafe))
                    dfirst_b, _ = first_vjp(da)
                    dfirst = macc(dfirst, dfirst_b, active_b & (v == 0))
                b_out[c] = da

            # ---- rotations ----
            fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
            bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]
            f_stack = jnp.stack(f_out)  # [vpp, ...]
            b_stack = jnp.stack(b_out)
            f_rot = jax.lax.ppermute(f_stack, axis, fwd_perm)
            b_rot = jax.lax.ppermute(b_stack, axis, bwd_perm)
            # wraparound lap shift: device 0 receives lap c data into
            # lap c+1 slots (fwd); device pp-1 receives lap c into c-1
            # (bwd). Lap 0 @ device 0 / lap vpp-1 @ device pp-1 take the
            # embed / dy paths instead, so their stale values are unused.
            f_shift = jnp.roll(f_rot, 1, axis=0)
            b_shift = jnp.roll(b_rot, -1, axis=0)
            f_next = jnp.where(s_idx == 0, f_shift, f_rot)
            b_next = jnp.where(s_idx == pp - 1, b_shift, b_rot)
            for c in range(vpp):
                new_fbuf[c] = f_next[c]
                new_bbuf[c] = b_next[c]

            return {
                "fbuf": new_fbuf, "bbuf": new_bbuf, "res": new_res,
                "dstage": new_dstage, "dfirst": dfirst, "dlast": dlast,
                "loss_sum": loss_sum,
            }, None

        final, _ = jax.lax.scan(tick, carry0, jnp.arange(T))

        inv = 1.0 / n_micro  # mean-loss convention
        dstage_local = jax.tree_util.tree_map(
            lambda *gs: jnp.stack(gs) * inv, *final["dstage"]
        )  # [vpp, ...]
        dfirst_out = jax.tree_util.tree_map(
            lambda g: (g * inv)[None], final["dfirst"])
        dlast_out = jax.tree_util.tree_map(
            lambda g: (g * inv)[None], final["dlast"])
        loss_out = (final["loss_sum"] * inv)[None]
        dstage_out = jax.tree_util.tree_map(
            lambda g: g[None], dstage_local)  # [1, vpp, ...] for P(axis)
        return loss_out, dfirst_out, dstage_out, dlast_out

    spec_sp = jax.tree_util.tree_map(lambda _: P(axis), dev_major)
    repl = jax.tree_util.tree_map(lambda _: P(), first_params)
    repl_l = jax.tree_util.tree_map(lambda _: P(), last_params)
    repl_x = jax.tree_util.tree_map(lambda _: P(), x_mbs)
    repl_a = jax.tree_util.tree_map(lambda _: P(), aux_mbs)
    out_spec = (
        P(axis),
        jax.tree_util.tree_map(lambda _: P(axis), first_params),
        jax.tree_util.tree_map(lambda _: P(axis), dev_major),
        jax.tree_util.tree_map(lambda _: P(axis), last_params),
    )
    fn = shard_map(
        per_device, mesh=mesh,
        in_specs=(spec_sp, repl, repl_l, repl_x, repl_a),
        out_specs=out_spec,
        axis_names={axis},
    )
    loss_st, dfirst_st, dstage_st, dlast_st = fn(
        dev_major, first_params, last_params, x_mbs, aux_mbs)
    # loss/dlast are real only on the last device's block; dfirst on the
    # first's — slice, never broadcast
    loss = loss_st[-1]
    dfirst = jax.tree_util.tree_map(lambda g: g[0], dfirst_st)
    dlast = jax.tree_util.tree_map(lambda g: g[-1], dlast_st)
    dstage = jax.tree_util.tree_map(
        lambda g: g.swapaxes(0, 1).reshape(V, *g.shape[2:]), dstage_st
    )
    return loss, dfirst, dstage, dlast


class SegmentPlan:
    """A concrete stage/chunk partition of an L-layer trunk.

    The stacked-parameter SPMD trunk stores all L layers on one leading
    dim; lockstep ticks need every stage to scan the SAME number of
    slots. A non-uniform partition (cost-balanced, or just L % parts
    != 0) is realized by PADDING each chunk to M = max chunk size:

    - ``pad_idx`` [parts, M]: gather indices into the logical [L] stack.
      Real slot j < size_c maps to layer bounds[c]+j; padding slots
      repeat the chunk's last layer (finite compute, output discarded).
    - inside the scan, slot j applies its layer only when j < n_active
      (``jnp.where`` to the carried activation otherwise), so padded
      slots are exact no-ops forward AND backward (zero cotangent).
    - ``unpad_idx`` [L]: positions of the real slots in the flattened
      [parts*M] padded stack — the transpose mapping for gradients. The
      duplicated padding indices receive only zeros under scatter-add,
      so gather(grads, unpad_idx) is exact.

    Parity: fleet pp_layers ``segment_layers`` with seg_method
    "layer:.*" / cost_fn — the reference assigns whole layers to stages
    (naturally ragged); here raggedness becomes masked padding because
    stages march in SPMD lockstep.
    """

    def __init__(self, costs, parts: int):
        import numpy as np

        self.bounds = segment_layers(costs, parts)
        self.parts = parts
        self.sizes = [b - a for a, b in
                      zip(self.bounds, self.bounds[1:])]
        self.M = max(self.sizes)
        self.uniform = min(self.sizes) == self.M
        L = self.bounds[-1]
        pad = np.zeros((parts, self.M), np.int32)
        unpad = np.zeros((L,), np.int32)
        for c, (a, s) in enumerate(zip(self.bounds, self.sizes)):
            for j in range(self.M):
                pad[c, j] = a + min(j, s - 1)
            for j in range(s):
                unpad[a + j] = c * self.M + j
        self.pad_idx = pad
        self.unpad_idx = unpad
        self.sizes_f32 = np.asarray(self.sizes, np.float32)

    def pack(self, tree):
        """Logical [L, ...] stacked leaves → padded [parts, M, ...] with
        a ``__n_active__`` [parts] leaf for the in-scan mask. Uniform
        plans reshape (no gather, no mask leaf) — the existing fast
        path."""
        if self.uniform:
            return jax.tree_util.tree_map(
                lambda v: v.reshape(self.parts, self.M, *v.shape[1:]),
                tree)
        out = jax.tree_util.tree_map(lambda v: v[self.pad_idx], tree)
        out["__n_active__"] = jnp.asarray(self.sizes_f32)
        return out

    def unpack_grads(self, tree):
        """Padded [parts, M, ...] grads → logical [L, ...] (drops the
        ``__n_active__`` cotangent)."""
        if self.uniform:
            return jax.tree_util.tree_map(
                lambda v: v.reshape(self.parts * self.M, *v.shape[2:]),
                tree)
        return {
            k: v.reshape(self.parts * self.M,
                         *v.shape[2:])[self.unpad_idx]
            for k, v in tree.items() if k != "__n_active__"
        }


def masked_chunk_scan(apply_one, chunk_params, h):
    """Scan ``apply_one`` over a chunk's stacked layer params, honoring
    the plan's padding mask: slot j is an exact identity (forward and
    backward) when j >= chunk_params["__n_active__"]. Without the mask
    leaf this is a plain scan (uniform plans)."""
    n_act = chunk_params.get("__n_active__") \
        if isinstance(chunk_params, dict) else None
    if n_act is None:
        def one(carry, lp):
            return apply_one(lp, carry), None

        out, _ = jax.lax.scan(one, h, chunk_params)
        return out
    weights = {k: v for k, v in chunk_params.items()
               if k != "__n_active__"}
    M = next(iter(weights.values())).shape[0]

    def one(carry, xs):
        j, lp = xs
        out = apply_one(lp, carry)
        return jnp.where(j < n_act, out, carry), None

    out, _ = jax.lax.scan(
        one, h, (jnp.arange(M, dtype=jnp.float32), weights))
    return out


def segment_layers(costs, num_stages: int):
    """Cost-balanced contiguous segmentation (parity: fleet pp_layers
    ``segment_layers`` with seg_method="layer:.*"/"uniform" — here the
    general balanced-partition form): split ``costs`` into
    ``num_stages`` contiguous groups minimizing the max group cost.
    Returns stage boundary indices [0, b1, ..., L]."""
    costs = list(costs)
    L = len(costs)
    if num_stages <= 0 or L < num_stages:
        raise ValueError(f"cannot split {L} layers into {num_stages} stages")
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + c)

    def greedy(cap):
        """Fill stages up to ``cap`` each (always leaving ≥1 layer per
        remaining stage). Returns bounds or None if infeasible."""
        bounds = [0]
        i = 0
        for stage in range(num_stages):
            start = i
            last_possible = L - (num_stages - stage - 1)
            while (i < last_possible
                   and (prefix[i + 1] - prefix[start] <= cap or i == start)):
                i += 1
            bounds.append(i)
        return bounds if bounds[-1] == L else None

    lo, hi = max(costs), prefix[-1]
    for _ in range(60):  # binary search the bottleneck stage cost
        mid = (lo + hi) / 2
        if greedy(mid) is not None:
            hi = mid
        else:
            lo = mid
    return greedy(hi)


class LayerDesc:
    """Parity: fleet LayerDesc — a deferred layer constructor."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Parity: fleet SharedLayerDesc — tied weights across stages (e.g.
    embedding/lm-head). All descs with the same ``key`` resolve to ONE
    built layer (one parameter set); a later occurrence may override the
    call with ``forward_func(layer, x)`` (the fleet convention for
    reusing the embedding matrix as the lm head). In the SPMD pipeline
    tied layers live outside the pipelined trunk (pre/post segments), so
    the shared parameter is one array with grads summed from both uses —
    no cross-stage weight sync step is needed (the reference needs an
    explicit allreduce between the tied stages)."""

    def __init__(self, key, layer_cls, *args, forward_func=None, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func


class PipelineLayer(Layer):
    """Parity: fleet PipelineLayer — segments a homogeneous trunk of
    LayerDescs into pp stages with layers_per_stage chunks each.

    TPU-native storage: ONE prototype layer defines the per-layer pytree;
    parameters for all L layers are stacked on a leading [L] dim
    (spec ("pp",) + the prototype's own spec shifted right), giving XLA
    the stacked layout pipeline_apply needs with zero copying.

    forward(x, n_micro) runs the pipelined trunk when a mesh with pp>1 is
    active, else a plain sequential scan (identical numerics).
    """

    def __init__(self, layer_desc: LayerDesc, num_layers: int,
                 num_stages: Optional[int] = None, seg_method="uniform",
                 costs=None):
        super().__init__()
        self.num_layers = num_layers
        self.num_stages = num_stages
        # per-layer costs for seg balancing (PipelineModule sets these
        # from cost_fn / seg_method); None → uniform
        self.costs = list(costs) if costs is not None else None
        self._plan_cache = {}
        self.prototype = layer_desc.build()
        # stack per-layer params: [L, *shape]
        protos = list(self.prototype.named_parameters())
        import numpy as np

        from ..core import random as random_mod
        from ..core.parameter import Parameter

        self._stacked_names = []
        for name, p in protos:
            init = p.init_fn or I.XavierNormal()
            if isinstance(p.value, jax.ShapeDtypeStruct):
                # meta-initialized prototype (core.meta.meta_init): the
                # stacked trunk stays abstract — 80×70B-scale layers
                # describable without allocating a byte (AOT memory
                # planning path)
                stacked = jax.ShapeDtypeStruct(
                    (num_layers,) + tuple(p.value.shape), p.value.dtype)
            else:
                vals = [p.value]
                for _ in range(num_layers - 1):
                    key = random_mod.next_rng_key("params")
                    vals.append(init(key, p.shape, p.dtype))
                stacked = jnp.stack(vals, axis=0)
            spec = ("pp",) + tuple(
                p.spec if p.spec is not None else [None] * p.ndim
            )
            flat = name.replace(".", "__")
            self.add_parameter(
                flat, Parameter(stacked, name=flat, spec=spec)
            )
            self._stacked_names.append((flat, name))

    def stage_params(self):
        return {flat: self._parameters[flat].value
                for flat, _ in self._stacked_names}

    def _apply_one(self, layer_params, x):
        """Run the prototype with one layer's params bound."""
        from ..core.functional import bind_params

        unflat = {orig: layer_params[flat]
                  for flat, orig in self._stacked_names}
        with bind_params(self.prototype, unflat):
            return self.prototype(x)

    def forward(self, x, n_micro: int = 1, mesh: Optional[Mesh] = None):
        from .sharding import current_mesh

        mesh = mesh or current_mesh()
        params = self.stage_params()
        pp = mesh.shape.get("pp", 1) if mesh is not None else 1
        if mesh is not None and pp > 1:
            if pp not in self._plan_cache:
                self._plan_cache[pp] = SegmentPlan(
                    self.costs or [1.0] * self.num_layers, pp)
            plan = self._plan_cache[pp]

            def stage_fn(stage_params, mb):
                return masked_chunk_scan(self._apply_one,
                                         stage_params, mb)

            # leading dim [L] -> padded [pp, M] (reshape when uniform)
            stacked = plan.pack(params)
            if x.shape[0] % n_micro == 0:
                mbs = x.reshape(n_micro, x.shape[0] // n_micro, *x.shape[1:])
            else:
                raise ValueError("batch not divisible by n_micro")
            ys = pipeline_apply(
                stage_fn, stacked, mbs, mesh=mesh, n_micro=n_micro
            )
            return ys.reshape(x.shape[0], *ys.shape[2:])
        # sequential fallback — same math, no pipeline
        def one(h, layer_params):
            return self._apply_one(layer_params, h), None

        h, _ = jax.lax.scan(one, x, params)
        return h


class PipelineModule(Layer):
    """Parity: fleet pp_layers.PipelineLayer taking a heterogeneous
    ``LayerDesc`` list — e.g. ``[SharedLayerDesc("embed", Embedding, ...),
    LayerDesc(Block, ...) * L, LayerNorm, SharedLayerDesc("embed", ...,
    forward_func=...)]``.

    TPU-native segmentation: the maximal homogeneous run of descs becomes
    the pipelined trunk (stacked params, SPMD ring — ``PipelineLayer``
    storage); everything before/after runs on the first/last (virtual)
    stage under plain GSPMD. ``segment_layers`` balances trunk layers per
    stage by cost. SharedLayerDescs with equal keys build once — tied
    parameters are genuinely one array.
    """

    def __init__(self, descs, num_stages: Optional[int] = None,
                 seg_method: str = "uniform", cost_fn=None):
        super().__init__()
        if seg_method != "uniform" and not seg_method.startswith("layer:"):
            raise ValueError(
                f"seg_method={seg_method!r}: expected 'uniform' or "
                "'layer:<regex>' (fleet pp_layers convention)")
        self.num_stages = num_stages
        self._shared = {}
        self._shared_fwd = {}

        sig = [self._sig(d) for d in descs]
        lo, hi = self._longest_run(sig)
        if hi - lo < 2:
            raise ValueError(
                "PipelineModule needs a homogeneous run of >=2 LayerDescs "
                "to pipeline (the transformer trunk)")
        self.trunk_range = (lo, hi)
        self.pre_descs = descs[:lo]
        self.post_descs = descs[hi:]
        # per-layer costs drive cost-balanced (possibly non-uniform)
        # segmentation — realized as masked padding in the SPMD trunk
        # (SegmentPlan); fleet seg_method="layer:<regex>" counts descs
        # whose class name matches, cost_fn overrides
        if cost_fn is not None:
            self.trunk_costs = [float(cost_fn(d)) for d in descs[lo:hi]]
            if not any(self.trunk_costs):
                raise ValueError(
                    "cost_fn returned 0 for every trunk layer — the "
                    "balanced partition is degenerate")
        elif seg_method.startswith("layer:"):
            import re

            pat = re.compile(seg_method[len("layer:"):])
            self.trunk_costs = [
                1.0 if pat.search(d.layer_cls.__name__) else 0.0
                for d in descs[lo:hi]]
            if not any(self.trunk_costs):
                raise ValueError(
                    f"seg_method={seg_method!r} matches no trunk layer "
                    f"({descs[lo].layer_cls.__name__})")
        else:
            self.trunk_costs = [1.0] * (hi - lo)
        self.trunk = PipelineLayer(descs[lo], hi - lo,
                                   num_stages=num_stages,
                                   costs=self.trunk_costs)
        self.pre = [self._build(d, f"pre_{i}")
                    for i, d in enumerate(self.pre_descs)]
        self.post = [self._build(d, f"post_{i}")
                     for i, d in enumerate(self.post_descs)]
        if num_stages:
            self.segments = segment_layers(self.trunk_costs, num_stages)

    @staticmethod
    def _sig(d):
        return (d.layer_cls, repr(d.args), repr(sorted(d.kwargs.items())),
                isinstance(d, SharedLayerDesc))

    @staticmethod
    def _longest_run(sig):
        best = (0, 0)
        i = 0
        while i < len(sig):
            j = i
            while j < len(sig) and sig[j] == sig[i] and not sig[i][3]:
                j += 1
            if j - i > best[1] - best[0]:
                best = (i, j)
            i = max(j, i + 1)
        return best

    def _build(self, desc, attr):
        if isinstance(desc, SharedLayerDesc):
            if desc.key not in self._shared:
                layer = desc.build()
                self._shared[desc.key] = layer
                self.add_sublayer(f"shared_{desc.key}", layer)
            self._shared_fwd[attr] = desc.forward_func
            return ("shared", desc.key, attr)
        layer = desc.build()
        self.add_sublayer(attr, layer)
        return ("own", attr, attr)

    def _apply_seq(self, entries, x):
        for kind, key, attr in entries:
            if kind == "shared":
                layer = self._shared[key]
                fwd = self._shared_fwd.get(attr)
                x = fwd(layer, x) if fwd is not None else layer(x)
            else:
                x = getattr(self, key)(x)
        return x

    def forward(self, x, n_micro: int = 1, mesh: Optional[Mesh] = None):
        """F-then-B (GPipe) forward — pre → pipelined trunk → post.
        Backward is jax autodiff (use ``PipelineTrainStep`` for 1F1B)."""
        x = self._apply_seq(self.pre, x)
        x = self.trunk(x, n_micro=n_micro, mesh=mesh)
        return self._apply_seq(self.post, x)


class PipelineTrainStep:
    """1F1B/VPP training step over a ``PipelineModule``.

    Parity: fleet PipelineParallel.train_batch with
    ``schedule_mode="1F1B"`` / ``vpp_degree`` (strategy.pipeline_configs)
    — here one jitted SPMD program per step built on
    ``pipeline_1f1b_step``. ``schedule_mode="F-then-B"`` falls back to
    autodiff through the GPipe forward.

    loss_fn(out_mb, aux_mb) -> scalar (mean over the microbatch).
    """

    def __init__(self, module: PipelineModule, optimizer, mesh: Mesh,
                 strategy=None, loss_fn=None, abstract: bool = False):
        self.module = module
        self.optimizer = optimizer
        self.mesh = mesh
        self.strategy = strategy
        self.loss_fn = loss_fn or (lambda out, aux: out.mean())
        pcfg = getattr(strategy, "pipeline_configs", None)
        self.schedule = getattr(pcfg, "schedule_mode", "1F1B")
        self.vpp = max(1, getattr(pcfg, "vpp_degree", 1))
        self.n_micro = max(1, getattr(pcfg, "accumulate_steps", 1))
        pp = mesh.shape["pp"]
        L = module.trunk.num_layers
        # cost-balanced chunking (SegmentPlan): uniform when L divides
        # evenly and costs are flat (zero-overhead reshape), masked
        # padding otherwise — L need not divide pp*vpp
        costs = getattr(module, "trunk_costs", None) or [1.0] * L
        self._plan_v = SegmentPlan(costs, pp * self.vpp)
        self._plan_pp = SegmentPlan(costs, pp)

        # flat param dicts (optimizer-compatible)
        pre_names = self._seq_param_names(module.pre)
        post_names = self._seq_param_names(module.post)
        trunk_p = module.trunk.stage_params()
        all_params = dict(module.named_parameters())
        self.params = {}
        for n in pre_names | post_names:
            self.params[n] = all_params[n].value
        for k, v in trunk_p.items():
            self.params[f"trunk.{k}"] = v
        self._pre_names, self._post_names = pre_names, post_names
        # pp × tp/fsdp composition: place every param according to its
        # logical spec over the mesh's non-pp axes BEFORE jit — the
        # shard_map handles the pp axis manually, GSPMD propagates the
        # rest through it (the trunk's stacked leading dim carries the
        # "pp" spec entry from PipelineLayer, so trunk weights live
        # pre-sharded per stage too). With strategy.sharding stage>=3 the
        # ZeRO-3 fsdp axis is folded in exactly as TrainStep does
        # (param_partition_spec), so stage-3×tp×pp composes.
        from .sharding import _filter_spec_for_mesh, param_partition_spec

        use_zero3 = (
            strategy is not None
            and getattr(strategy, "sharding", False)
            and getattr(strategy, "sharding_stage", 0) >= 3
            and "fsdp" in mesh.shape and mesh.shape["fsdp"] > 1
        )
        self.abstract = abstract
        self.param_shardings = {}
        for n in self.params:
            # trunk params appear in named_parameters() under the same
            # "trunk.<flat>" keys stage_params() uses
            obj = all_params.get(n)
            spec = getattr(obj, "spec", None)
            if spec is None:
                spec = (None,) * jnp.ndim(self.params[n])
            active_plan = (self._plan_v if self.schedule.upper()
                           in ("1F1B", "VPP") else self._plan_pp)
            if (n.startswith("trunk.") and not active_plan.uniform
                    and tuple(spec)[:1] == ("pp",)):
                # non-uniform plan: the logical [L] stack is not
                # pp-divisible — keep it replicated on the leading dim;
                # the in-jit pack() gather lands it in the shard_map's
                # P("pp") layout
                spec = (None,) + tuple(spec)[1:]
            spec = _filter_spec_for_mesh(tuple(spec), mesh)
            if use_zero3:
                pspec = param_partition_spec(
                    n, tuple(self.params[n].shape), spec, strategy)
            else:
                pspec = P(*spec)
            sh = NamedSharding(mesh, pspec)
            self.param_shardings[n] = sh
            if abstract:
                v = self.params[n]
                self.params[n] = jax.ShapeDtypeStruct(
                    tuple(v.shape), v.dtype, sharding=sh)
            else:
                self.params[n] = jax.device_put(self.params[n], sh)
        if abstract:
            # mirror the eager path's sharding semantics: zeros_like on a
            # committed array inherits its sharding, so any state leaf
            # shaped like its parameter gets the parameter's sharding
            state_shape = jax.eval_shape(optimizer.init, self.params)

            def _attach(name, leaf):
                sh = self.param_shardings.get(name)
                if sh is not None and tuple(leaf.shape) == tuple(
                        self.params[name].shape):
                    return jax.ShapeDtypeStruct(
                        tuple(leaf.shape), leaf.dtype, sharding=sh)
                return jax.ShapeDtypeStruct(
                    tuple(leaf.shape), leaf.dtype,
                    sharding=NamedSharding(mesh, P()))

            self.opt_state = {"step": jax.ShapeDtypeStruct(
                tuple(state_shape["step"].shape), state_shape["step"].dtype,
                sharding=NamedSharding(mesh, P()))}
            self.opt_state["slots"] = {
                n: {k: _attach(n, v) for k, v in slots.items()}
                for n, slots in state_shape["slots"].items()}
            if "master" in state_shape:
                self.opt_state["master"] = {
                    n: _attach(n, v)
                    for n, v in state_shape["master"].items()}
        else:
            self.opt_state = optimizer.init(self.params)
        self._step = jax.jit(self._make_step())

    def lower(self, x_shapes, aux_shapes):
        """AOT-lower the pipelined step with abstract inputs (use with
        ``abstract=True``); ``.compile().memory_analysis()`` yields the
        per-device byte plan for configs larger than host memory."""
        from .sharding import mesh_context

        def _sds(v, shard_batch):
            entries = [None] * len(v.shape)
            if shard_batch and len(v.shape) and "dp" in self.mesh.shape:
                entries[0] = "dp"
            return jax.ShapeDtypeStruct(
                tuple(v.shape), v.dtype,
                sharding=NamedSharding(self.mesh, P(*entries)))

        x = jax.tree_util.tree_map(lambda v: _sds(v, True), x_shapes)
        aux = jax.tree_util.tree_map(lambda v: _sds(v, True), aux_shapes)
        with mesh_context(self.mesh):
            return self._step.lower(self.params, self.opt_state, x, aux)

    def _seq_param_names(self, entries):
        names = set()
        all_params = dict(self.module.named_parameters())
        for kind, key, attr in entries:
            prefix = f"shared_{key}." if kind == "shared" else f"{attr}."
            names |= {n for n in all_params if n.startswith(prefix)}
        return names

    def _make_step(self):
        module = self.module
        mesh, vpp = self.mesh, self.vpp
        pp = mesh.shape["pp"]
        V = pp * vpp
        loss_fn = self.loss_fn
        from ..core.functional import bind_params

        def first_fn(first_params, x_mb):
            with bind_params(module, first_params):
                return module._apply_seq(module.pre, x_mb)

        # strategy.recompute → per-LAYER jax.checkpoint inside the chunk
        # scan. The chunk-level remat in pipeline_1f1b_step alone is not
        # enough at scale: the chunk's backward re-materializes every
        # layer's internals at once (attention scores, MLP intermediates
        # for all layers_per_stage layers live simultaneously). Nesting a
        # checkpoint per scanned layer caps the peak at one layer's
        # internals + the chunk's layer-boundary activations — the
        # memory shape the reference's per-layer RecomputeLayer gives its
        # pipeline (fleet.meta_parallel pp_layers + recompute).
        per_layer_remat = bool(getattr(self.strategy, "recompute", False))
        apply_one = (jax.checkpoint(module.trunk._apply_one)
                     if per_layer_remat else module.trunk._apply_one)

        def stage_fn(chunk_params, h):
            # chunk leaves: [per_chunk(+pad), ...] — scan the prototype
            # over them, honoring the plan's padding mask if present
            return masked_chunk_scan(apply_one, chunk_params, h)

        def last_fn(last_params, y, aux):
            with bind_params(module, last_params):
                out = module._apply_seq(module.post, y)
            return loss_fn(out, aux)

        n_micro = self.n_micro
        schedule = self.schedule

        def step_fn(params, opt_state, x, aux):
            from .sharding import suppress_constraints

            # GSPMD activation hints inside the model body cannot apply
            # to pp-varying values in the manual shard_map region — trace
            # the whole step with hints off
            with suppress_constraints():
                return _step_body(params, opt_state, x, aux)

        def _step_body(params, opt_state, x, aux):
            first_params = {n: params[n] for n in self._pre_names}
            last_params = {n: params[n] for n in self._post_names}
            trunk_params = {
                k[len("trunk."):]: v for k, v in params.items()
                if k.startswith("trunk.")
            }
            mbs = jax.tree_util.tree_map(
                lambda a: a.reshape(n_micro, a.shape[0] // n_micro,
                                    *a.shape[1:]), x)
            aux_mbs = jax.tree_util.tree_map(
                lambda a: a.reshape(n_micro, a.shape[0] // n_micro,
                                    *a.shape[1:]), aux)
            if schedule.upper() in ("1F1B", "VPP"):
                sp = self._plan_v.pack(trunk_params)
                loss, dfirst, dstage, dlast = pipeline_1f1b_step(
                    first_fn, stage_fn, last_fn,
                    first_params, sp, last_params, mbs, aux_mbs,
                    mesh=mesh, vpp=vpp)
                grads = {}
                for n in set(dfirst) | set(dlast):
                    g = None
                    if n in dfirst:
                        g = dfirst[n]
                    if n in dlast:  # tied params: sum both uses' grads
                        g = dlast[n] if g is None else g + dlast[n]
                    grads[n] = g
                for k, v in self._plan_v.unpack_grads(dstage).items():
                    grads[f"trunk.{k}"] = v
            else:  # F-then-B: autodiff through the GPipe forward
                def loss_of(p):
                    fpp = {n: p[n] for n in self._pre_names}
                    lpp = {n: p[n] for n in self._post_names}
                    tpp = {k[len("trunk."):]: v for k, v in p.items()
                           if k.startswith("trunk.")}
                    h0 = jax.vmap(lambda xm: first_fn(fpp, xm))(mbs)
                    # stage slice leaves arrive [layers_per_stage(+pad),
                    # ...] — exactly what stage_fn's masked scan consumes
                    ys = pipeline_apply(
                        stage_fn, self._plan_pp.pack(tpp),
                        h0, mesh=mesh, n_micro=n_micro)
                    losses = jax.vmap(
                        lambda y, a: last_fn(lpp, y, a))(ys, aux_mbs)
                    return losses.mean()

                loss, grads = jax.value_and_grad(loss_of)(params)
            new_params, new_state = self.optimizer.update(
                grads, opt_state, params)
            return new_params, new_state, loss

        return step_fn

    def run(self, x, aux):
        from .sharding import mesh_context

        if self.abstract:
            raise RuntimeError(
                "PipelineTrainStep(abstract=True) holds no real "
                "parameters; use lower() for AOT compilation")
        with mesh_context(self.mesh):
            self.params, self.opt_state, loss = self._step(
                self.params, self.opt_state, x, aux)
        return loss
