"""Launch CLI (parity: python -m paddle.distributed.launch —
python/paddle/distributed/launch/: Context arg/env parsing,
CollectiveController building a Job of Pod/Containers, per-rank process
supervision with log capture, master rendezvous).

TPU-native: on TPU pods there is one process per host (not per chip), and
``jax.distributed`` handles rendezvous via the coordinator address. The
controller therefore launches ``nproc_per_node`` worker processes (>1
only for CPU/debug meshes), wires the PADDLE_* env contract the rest of
the framework reads (env.py), captures per-rank logs to
``log/workerlog.N``, supervises exits, and — with ``--elastic`` — re-spawns
failed workers so training resumes from the latest checkpoint
(checkpoint-resume recovery, the reference's elastic semantics with etcd
replaced by the coordinator; SURVEY.md §5 "Failure detection").
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional


class Container:
    def __init__(self, rank: int, cmd: List[str], env: dict, log_dir: str):
        self.rank = rank
        self.cmd = cmd
        self.env = env
        self.log_dir = log_dir
        self.proc: Optional[subprocess.Popen] = None
        self.log_file = None

    def start(self):
        os.makedirs(self.log_dir, exist_ok=True)
        path = os.path.join(self.log_dir, f"workerlog.{self.rank}")
        self.log_file = open(path, "ab")
        self.proc = subprocess.Popen(
            self.cmd, env=self.env, stdout=self.log_file,
            stderr=subprocess.STDOUT,
        )
        return self.proc

    def poll(self):
        return self.proc.poll() if self.proc else None

    def terminate(self):
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        if self.log_file:
            self.log_file.close()


class CollectiveController:
    def __init__(self, args, extra: List[str]):
        self.args = args
        self.extra = extra
        self.containers: List[Container] = []
        self.manager = None
        if args.np:
            # elastic membership via the shared-store ElasticManager
            # (reference: fleet/elastic with etcd; SURVEY.md §5)
            from ..elastic import ElasticManager, FileStore, parse_np_range

            store = FileStore(args.elastic_store, args.job_id)
            self.manager = ElasticManager(
                store, parse_np_range(args.np),
                fault_timeout=args.elastic_timeout)
            self.manager.register()

    def _world(self, grace: bool = False):
        if self.manager is None:
            return self.args.nnodes, self.args.node_rank
        if grace:
            # restart path: let a dead peer's heartbeat go stale before
            # re-ranking, or the rebuilt world still contains it and the
            # respawn burns max_restarts against a doomed membership
            time.sleep(self.manager.fault_timeout)
        self.manager.evict_faulted()
        spec = self.manager.wait_for_world(
            timeout=self.args.elastic_timeout * 6,
            settle=self.args.elastic_settle)
        if spec is None:
            raise RuntimeError(
                "elastic: no viable membership within timeout "
                f"(need np in [{self.manager.min_np}, "
                f"{self.manager.max_np}])")
        return spec.nnodes, spec.node_rank

    def build(self, grace: bool = False):
        nproc = self.args.nproc_per_node
        master = self.args.master or "127.0.0.1:49175"
        nnodes, node_rank = self._world(grace=grace)
        self.containers = []
        for local_rank in range(nproc):
            rank = node_rank * nproc + local_rank
            env = dict(os.environ)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(nnodes * nproc),
                "PADDLE_LOCAL_RANK": str(local_rank),
                "PADDLE_MASTER": master,
                "COORDINATOR_ADDRESS": master,
            })
            if self.args.devices:
                env["CUDA_VISIBLE_DEVICES"] = self.args.devices
            cmd = [sys.executable] + self.extra
            self.containers.append(
                Container(rank, cmd, env, self.args.log_dir)
            )
        return self

    def run(self) -> int:
        for c in self.containers:
            c.start()
        print(
            f"launched {len(self.containers)} worker(s); logs in "
            f"{self.args.log_dir}/workerlog.N"
        )
        restarts = 0
        try:
            while True:
                statuses = [c.poll() for c in self.containers]
                if all(s == 0 for s in statuses):
                    return 0
                failed = [
                    (i, s) for i, s in enumerate(statuses)
                    if s not in (None, 0)
                ]
                if failed:
                    if (self.args.elastic
                            and restarts < self.args.max_restarts):
                        restarts += 1
                        print(
                            f"worker(s) {[i for i, _ in failed]} failed; "
                            f"elastic restart {restarts}/"
                            f"{self.args.max_restarts}"
                        )
                        for c in self.containers:
                            c.terminate()
                        # re-rank over the surviving membership before
                        # respawning (no-op without --np)
                        try:
                            self.build(grace=self.manager is not None)
                        except RuntimeError as e:
                            print(f"elastic: {e}; tearing down")
                            for c in self.containers:
                                c.terminate()
                            return 1
                        for c in self.containers:
                            c.start()
                    else:
                        print(
                            f"worker(s) failed with {failed}; tearing down"
                        )
                        for c in self.containers:
                            c.terminate()
                        return 1
                time.sleep(self.args.poll_interval)
        except KeyboardInterrupt:
            for c in self.containers:
                c.terminate()
            return 130

    def stop(self):
        for c in self.containers:
            c.terminate()
        if self.manager is not None:
            self.manager.deregister()


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="multi-process / multi-host job launcher",
    )
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")))
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_MASTER"))
    p.add_argument("--devices", type=str, default=None)
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--elastic", action="store_true",
                   help="restart failed workers (checkpoint-resume)")
    p.add_argument("--np", type=str, default=None,
                   help="elastic node range 'min:max' (implies membership "
                        "tracking via --elastic_store)")
    p.add_argument("--job_id", type=str,
                   default=os.environ.get("PADDLE_JOB_ID", "default"))
    p.add_argument("--elastic_store", type=str,
                   default=os.environ.get("PADDLE_ELASTIC_STORE", "/tmp"),
                   help="shared directory for membership (must be a "
                        "filesystem ALL nodes see — NFS/GCS-fuse; the "
                        "/tmp default only works single-node)")
    p.add_argument("--elastic_timeout", type=float, default=5.0)
    p.add_argument("--elastic_settle", type=float, default=1.0,
                   help="membership must be stable this long before a "
                        "world forms (startup race debounce)")
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("--poll_interval", type=float, default=1.0)
    p.add_argument("training_script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch(argv=None) -> int:
    args = parse_args(argv)
    if args.np and args.elastic_store == "/tmp" and \
            parse_np_max(args.np) > 1:
        print("warning: --elastic_store=/tmp is node-local; multi-node "
              "membership needs a shared filesystem path", file=sys.stderr)
    extra = [args.training_script] + list(args.script_args)
    controller = CollectiveController(args, extra)
    try:
        # build inside the try: a membership-wait timeout in the first
        # build must still deregister the heartbeat, or the ghost node
        # corrupts the next launch's world
        controller.build()
        return controller.run()
    finally:
        controller.stop()


def parse_np_max(np_arg: str) -> int:
    from ..elastic import parse_np_range

    return parse_np_range(np_arg)[1]
