"""Elastic node management (parity: python/paddle/distributed/fleet/
elastic/manager.py — ``ElasticManager``: etcd node registry, fault
watch, scale up/down within ``--np min:max``, restart signaling).

TPU-native substitution: there is no etcd on a TPU pod; the natural
shared medium is the job's shared filesystem (NFS / GCS-fuse — the same
place checkpoints go) plus the JAX coordinator for in-job barriers. The
registry here is a directory of per-node heartbeat files: registration
writes one, a daemon thread refreshes its mtime, and the manager treats
a stale mtime as node failure — the same liveness contract the
reference implements with etcd leases. Recovery is checkpoint-resume
(the reference's semantics too: trainers exit and relaunch with
re-ranked envs; no in-flight state survives).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass
class WorldSpec:
    """What a relaunch needs: the surviving membership, re-ranked.

    ``hosts`` carries actual hostnames (what a relaunch command / trainer
    endpoint list needs); ``node_ids`` the registry keys that determined
    the ranking (hostname_pid — unique even with several nodes per
    host)."""

    nnodes: int
    node_rank: int
    hosts: List[str]
    node_ids: List[str]


def parse_np_range(np_arg: str) -> Tuple[int, int]:
    """'2:4' → (2, 4); '4' → (4, 4) (reference --np syntax)."""
    if ":" in np_arg:
        lo, hi = np_arg.split(":")
        return int(lo), int(hi)
    return int(np_arg), int(np_arg)


class FileStore:
    """Heartbeat registry on a shared directory (etcd-lease analog)."""

    def __init__(self, root: str, job_id: str):
        self.dir = os.path.join(root, f"elastic_{job_id}")
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, node_id: str) -> str:
        return os.path.join(self.dir, f"node_{node_id}.json")

    def write(self, node_id: str, payload: dict):
        path = self._path(node_id)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)  # atomic on POSIX

    def touch(self, node_id: str):
        os.utime(self._path(node_id))

    def remove(self, node_id: str):
        try:
            os.remove(self._path(node_id))
        except FileNotFoundError:
            pass

    def nodes(self) -> Dict[str, dict]:
        out = {}
        for name in sorted(os.listdir(self.dir)):
            if not (name.startswith("node_") and name.endswith(".json")):
                continue
            path = os.path.join(self.dir, name)
            try:
                with open(path) as f:
                    info = json.load(f)
                info["_mtime"] = os.path.getmtime(path)
                out[name[len("node_"):-len(".json")]] = info
            except (OSError, json.JSONDecodeError):
                continue  # racing writer; next poll sees it
        return out


class ElasticManager:
    """Node-membership watcher + re-ranker.

    One instance runs per node. ``register()`` announces the node and
    starts the heartbeat daemon; ``scan()`` classifies the membership;
    ``plan()`` returns the re-ranked WorldSpec when the membership is
    viable (min_np ≤ alive ≤ max_np), or None while waiting.
    """

    def __init__(self, store: FileStore, np_range: Tuple[int, int],
                 node_id: Optional[str] = None,
                 heartbeat_interval: float = 1.0,
                 fault_timeout: float = 5.0):
        self.store = store
        self.min_np, self.max_np = np_range
        self.node_id = node_id or f"{socket.gethostname()}_{os.getpid()}"
        self.heartbeat_interval = heartbeat_interval
        self.fault_timeout = fault_timeout
        self._hb_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---- node side ----
    def register(self, host: Optional[str] = None):
        self.store.write(self.node_id, {
            "host": host or socket.gethostname(),
            "pid": os.getpid(),
            "registered_at": time.time(),
        })
        self._stop.clear()
        self._hb_thread = threading.Thread(target=self._beat, daemon=True)
        self._hb_thread.start()
        return self

    def _beat(self):
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self.store.touch(self.node_id)
            except FileNotFoundError:
                return  # deregistered under us

    def deregister(self):
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
        self.store.remove(self.node_id)

    # ---- watcher side ----
    def scan(self) -> Tuple[List[str], List[str]]:
        """→ (alive node ids, faulted node ids) by heartbeat age."""
        now = time.time()
        alive, faulted = [], []
        for nid, info in self.store.nodes().items():
            if now - info["_mtime"] > self.fault_timeout:
                faulted.append(nid)
            else:
                alive.append(nid)
        return alive, faulted

    def evict_faulted(self) -> List[str]:
        """Drop stale registrations (the etcd-lease-expiry analog)."""
        _, faulted = self.scan()
        for nid in faulted:
            self.store.remove(nid)
        return faulted

    def plan(self) -> Optional[WorldSpec]:
        """Re-ranked world over the live membership, or None if the job
        cannot (yet) run: ranks are assigned by sorted node id, so every
        node computes the identical assignment without coordination."""
        alive, _ = self.scan()
        if not (self.min_np <= len(alive) <= self.max_np):
            return None
        node_ids = sorted(alive)
        if self.node_id not in node_ids:
            return None
        registry = self.store.nodes()
        hosts = [
            registry.get(nid, {}).get("host", nid.rsplit("_", 1)[0])
            for nid in node_ids
        ]
        return WorldSpec(nnodes=len(node_ids),
                         node_rank=node_ids.index(self.node_id),
                         hosts=hosts,
                         node_ids=node_ids)

    def wait_for_world(self, timeout: float = 60.0,
                       poll: float = 0.5,
                       settle: float = 0.0) -> Optional[WorldSpec]:
        """Block until a viable membership forms (optionally stable for
        ``settle`` seconds — the reference's scale-up debounce)."""
        deadline = time.time() + timeout
        stable_since = None
        last = None
        while time.time() < deadline:
            spec = self.plan()
            if spec is not None:
                key = tuple(spec.node_ids)
                if key != last:
                    last, stable_since = key, time.time()
                if time.time() - stable_since >= settle:
                    return spec
            else:
                last, stable_since = None, None
            time.sleep(poll)
        return None


def latest_checkpoint(ckpt_root: str, prefix: str = "step_"
                      ) -> Optional[str]:
    """Newest complete checkpoint dir (the resume point after an elastic
    restart). A checkpoint counts only when it is committed (COMMITTED
    marker / merged metadata) — torn ``.tmp`` dirs from the killed
    incarnation are skipped."""
    from .checkpoint import is_committed

    if not os.path.isdir(ckpt_root):
        return None
    best, best_step = None, -1
    for name in os.listdir(ckpt_root):
        if not name.startswith(prefix):
            continue
        if not is_committed(os.path.join(ckpt_root, name)):
            continue
        try:
            step = int(name[len(prefix):])
        except ValueError:
            continue
        if step > best_step:
            best, best_step = os.path.join(ckpt_root, name), step
    return best
