"""paddle.distributed.stream namespace (parity:
python/paddle/distributed/communication/stream/): the stream-explicit
collective variants. XLA owns stream scheduling on TPU — collectives
are HLO ops ordered by the compiler — so these are the same collectives
with ``sync_op``/``use_calc_stream`` accepted and ignored."""

from __future__ import annotations

import functools

from . import collective as _c


def _streamified(fn):
    @functools.wraps(fn)
    def wrapper(*args, sync_op=True, use_calc_stream=False, **kw):
        out = fn(*args, **kw)
        if not sync_op:
            # paddle's async contract returns a waitable task
            return _c._Task(out)
        return out

    return wrapper


all_reduce = _streamified(_c.all_reduce)
all_gather = _streamified(_c.all_gather)
reduce_scatter = _streamified(_c.reduce_scatter)
broadcast = _streamified(_c.broadcast)
reduce = _streamified(_c.reduce)
scatter = _streamified(_c.scatter)
alltoall = _streamified(_c.alltoall)
alltoall_single = _streamified(_c.alltoall_single)
send = _streamified(_c.send)
recv = _streamified(_c.recv)
