"""paddle.distributed.fleet namespace shim (parity:
python/paddle/distributed/fleet/__init__.py — the API most migrating
training scripts drive: ``fleet.init(is_collective=True, strategy)``,
``fleet.distributed_model/optimizer``, rank/worker queries).

On TPU the heavy machinery behind these calls (DDP reducer, sharded
optimizer wrappers, communication overlap) is GSPMD's job — the wrapped
objects come back unchanged and parallelism comes from the mesh +
shardings consumed by TrainStep. The namespace keeps the call sites
working and routes the strategy into the global HCG.
"""

from __future__ import annotations

from typing import Optional

from .strategy import DistributedStrategy  # noqa: F401
from .topology import (
    HybridCommunicateGroup,  # noqa: F401
    fleet_init,
    get_hybrid_communicate_group,  # noqa: F401
)
from .env import get_rank, get_world_size
from . import parallel_layers as meta_parallel  # noqa: F401


_strategy: Optional[DistributedStrategy] = None


def init(role_maker=None, is_collective=False,
         strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
    """Parity: fleet.init. Builds the global HybridCommunicateGroup from
    the strategy's hybrid_configs (collective mode; parameter-server
    role makers are N/A on TPU — see MAPPING.md)."""
    global _strategy
    _strategy = strategy or DistributedStrategy()
    fleet_init(_strategy)
    return None


def is_first_worker() -> bool:
    return get_rank() == 0


def worker_index() -> int:
    return get_rank()


def worker_num() -> int:
    return get_world_size()


def barrier_worker():
    from .collective import barrier

    barrier()


def distributed_model(model):
    """Parity: fleet.distributed_model — upstream wraps with the DDP
    reducer; GSPMD inserts gradient reductions from shardings, so the
    model passes through."""
    return model


def distributed_optimizer(optimizer, strategy=None):
    """Parity: fleet.distributed_optimizer — upstream chains
    meta-optimizers (sharding/amp/recompute passes); here those are
    TrainStep concerns driven by the SAME strategy object, so the
    optimizer passes through."""
    global _strategy
    if strategy is not None:
        _strategy = strategy
    return optimizer


def get_strategy() -> Optional[DistributedStrategy]:
    return _strategy
