"""The sharding engine: every Fleet parallelism strategy as GSPMD rules.

Parity map (SURVEY.md §2.2):
  - DP (paddle.DataParallel + imperative::Reducer bucketed allreduce) →
    batch-axis sharding over "dp"; XLA emits the gradient reduce and
    overlaps it with backward compute (the Reducer's whole job).
  - Sharding stage 1/2 (DygraphShardingOptimizer / GroupShardedStage2,
    fleet/meta_parallel/sharding/) → optimizer-state (and transient-grad)
    sharding over "fsdp": params stay replicated, moments/master are
    sharded; XLA inserts reduce-scatter before the update and keeps the
    weight all-gather out of it.
  - Sharding stage 3 (GroupShardedStage3: param shards, pre-forward
    allgather, post-backward release) → parameters themselves sharded
    over "fsdp"; XLA schedules the all-gather just-in-time per layer and
    frees gathered copies — the prefetch/release hooks fall out of the
    compiler's liveness analysis.
  - TP (ColumnParallelLinear etc., mp_layers.py) → per-dim "tp" entries in
    Parameter.spec (see parallel_layers/mp_layers.py here).
  - Megatron-SP (sequence_parallel_utils.py) → activation constraints
    sharding the sequence dim over "tp" between TP regions.
  - SEP/Ulysses (topology "sep" axis) → sequence dim sharded over "sep",
    all-to-all around attention (kernels/ulysses.py).

No per-parameter communication code exists anywhere: the *only* artifacts
are PartitionSpecs. That is the TPU-native translation of ~30k lines of
group-sharded python/C++ in the reference.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .strategy import DistributedStrategy

_mesh_var: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_mesh", default=None
)


@contextlib.contextmanager
def mesh_context(mesh: Mesh):
    """Make ``mesh`` the ambient mesh for shard_activation constraints.

    Must be active at *trace* time (the trainer wraps jit calls in it).
    """
    tok = _mesh_var.set(mesh)
    try:
        yield mesh
    finally:
        _mesh_var.reset(tok)


def current_mesh() -> Optional[Mesh]:
    m = _mesh_var.get()
    if m is not None:
        return m
    from .topology import get_hybrid_communicate_group

    hcg = get_hybrid_communicate_group()
    return hcg.mesh if hcg is not None else None

# params smaller than this stay unsharded under ZeRO-3 (parity:
# GroupShardedStage3 segment_size keeps small params whole)
MIN_SIZE_TO_SHARD = 2**13


def _normalize_logical_spec(spec, ndim) -> Tuple:
    if spec is None:
        return tuple([None] * ndim)
    spec = tuple(spec)
    if len(spec) < ndim:
        spec = spec + tuple([None] * (ndim - len(spec)))
    return spec


def _axes_used(spec) -> set:
    used = set()
    for e in spec:
        if e is None:
            continue
        if isinstance(e, (tuple, list)):
            used.update(e)
        else:
            used.add(e)
    return used


def fsdp_augment(spec: Tuple, shape, axis_name: str = "fsdp",
                 axis_size: int = 1) -> Tuple:
    """Add the fsdp axis to the best unsharded dim (prefer dim 0; prefer
    divisible dims; fall back to the largest)."""
    if axis_name in _axes_used(spec):
        return spec
    candidates = [i for i, e in enumerate(spec) if e is None and shape[i] > 1]
    if not candidates:
        # compose onto an already-sharded dim if divisible
        for i, e in enumerate(spec):
            if e is not None and shape[i] % max(axis_size, 1) == 0:
                cur = e if isinstance(e, tuple) else (e,)
                out = list(spec)
                out[i] = cur + (axis_name,)
                return tuple(out)
        return spec
    divisible = [i for i in candidates if shape[i] % max(axis_size, 1) == 0]
    pool = divisible or candidates
    dim = min(pool)  # prefer leading dim (weight rows / vocab / out_c)
    out = list(spec)
    out[dim] = axis_name
    return tuple(out)


def param_partition_spec(
    name: str,
    shape,
    logical_spec,
    strategy: DistributedStrategy,
) -> P:
    """Final PartitionSpec for a parameter array."""
    ndim = len(shape)
    spec = _normalize_logical_spec(logical_spec, ndim)
    stage = strategy.sharding_stage
    size = int(np.prod(shape)) if ndim else 1
    if stage >= 3 and strategy.fsdp > 1 and size >= MIN_SIZE_TO_SHARD:
        spec = fsdp_augment(spec, shape, "fsdp", strategy.fsdp)
    return P(*spec)


def opt_slot_partition_spec(
    name: str,
    shape,
    logical_spec,
    strategy: DistributedStrategy,
) -> P:
    """PartitionSpec for optimizer moments / master weights: sharded over
    fsdp from stage 1 up (ZeRO-1's entire point)."""
    ndim = len(shape)
    spec = _normalize_logical_spec(logical_spec, ndim)
    stage = strategy.sharding_stage
    size = int(np.prod(shape)) if ndim else 1
    if stage >= 1 and strategy.fsdp > 1 and size >= MIN_SIZE_TO_SHARD:
        spec = fsdp_augment(spec, shape, "fsdp", strategy.fsdp)
    return P(*spec)


def batch_spec(ndim: int = 2, seq_axis: Optional[int] = 1,
               strategy: Optional[DistributedStrategy] = None) -> P:
    """Input batch sharding: batch over (dp, fsdp), sequence over sep."""
    entries = [None] * ndim
    entries[0] = ("dp", "fsdp")
    if seq_axis is not None and ndim > seq_axis and (
        strategy is None or strategy.sep > 1
    ):
        entries[seq_axis] = "sep"
    return P(*entries)


def model_shardings(
    model,
    mesh: Mesh,
    strategy: DistributedStrategy,
    filter_to_mesh: bool = False,
) -> Dict[str, NamedSharding]:
    """NamedSharding per parameter (keys = qualified names).

    ``filter_to_mesh``: drop logical axes the mesh doesn't carry (the
    serving engine's placement path — the same model runs under any
    topology)."""
    out = {}
    for name, p in model.named_parameters():
        spec = param_partition_spec(name, p.shape, p.spec, strategy)
        if filter_to_mesh:
            spec = P(*_filter_spec_for_mesh(tuple(spec), mesh))
        out[name] = NamedSharding(mesh, spec)
    return out


def opt_state_shardings(optimizer, params_meta, mesh, strategy):
    """Build the sharding pytree matching Optimizer.init's state layout.

    ``params_meta``: {name: (shape, logical_spec)}.
    """
    slot_shardings = {}
    master = {}
    for name, (shape, lspec) in params_meta.items():
        spec = opt_slot_partition_spec(name, shape, lspec, strategy)
        sh = NamedSharding(mesh, spec)
        # probe slot structure with a zero-init (shapes only)
        import jax.numpy as jnp

        class _Meta:
            pass

        meta = _Meta()
        meta.shape = shape
        meta.dtype = jnp.float32
        slots = optimizer._init_slot(meta)
        slot_shardings[name] = {
            k: (sh if getattr(v, "shape", ()) == tuple(shape)
                else NamedSharding(mesh, P()))
            for k, v in slots.items()
        }
        master[name] = sh
    state_shardings = {
        "step": NamedSharding(mesh, P()),
        "slots": slot_shardings,
    }
    if optimizer.multi_precision:
        # master entries exist only for low-precision params; caller prunes
        state_shardings["master"] = master
    return state_shardings


def _filter_spec_for_mesh(spec_entries, mesh: Mesh):
    names = set(mesh.axis_names)

    def keep(e):
        if e is None:
            return None
        if isinstance(e, (tuple, list)):
            kept = tuple(a for a in e if a in names)
            return kept if len(kept) > 1 else (kept[0] if kept else None)
        return e if e in names else None

    return tuple(keep(e) for e in spec_entries)


_suppress_var: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_suppress_constraints", default=False
)


@contextlib.contextmanager
def suppress_constraints():
    """Disable shard_activation hints while tracing — needed inside
    manual-axis shard_map regions (the 1F1B pipeline): a GSPMD
    with_sharding_constraint cannot be applied to a pp-varying value
    against a mesh whose pp axis is Auto-typed. Constraints are hints;
    GSPMD still propagates shardings from the operands without them."""
    tok = _suppress_var.set(True)
    try:
        yield
    finally:
        _suppress_var.reset(tok)


def shard_activation(x, *spec_entries):
    """with_sharding_constraint against the ambient mesh; no-op when no
    mesh is active (single-device eager use) or when constraints are
    suppressed (inside manual-axis pipeline bodies). Axis names absent
    from the mesh are dropped, so the same model code runs under any
    topology."""
    mesh = current_mesh()
    if mesh is None or _suppress_var.get():
        return x
    spec = _filter_spec_for_mesh(spec_entries, mesh)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec))
    )


def sequence_parallel_constraint(x):
    """Megatron-SP: shard [batch, seq, hidden] activations' sequence dim
    over the tp axis between TP regions (parity:
    fleet/utils/sequence_parallel_utils.py AllGather/ReduceScatter ops —
    GSPMD derives those collectives from this constraint)."""
    return shard_activation(x, ("dp", "fsdp"), ("sep", "tp"), None)


def place_params_on_mesh(model, mesh, strategy):
    """Eagerly reshard a model's parameter values onto the mesh (host →
    sharded device arrays). Parity: the initial broadcast/scatter
    DataParallel & GroupShardedStage3 do at wrap time."""
    for name, p in model.named_parameters():
        spec = param_partition_spec(name, p.shape, p.spec, strategy)
        p.value = jax.device_put(p.value, NamedSharding(mesh, spec))
    return model


def recompute(function, *args, **kwargs):
    """Parity: paddle.distributed.fleet.utils.recompute — run ``function``
    without saving intermediate activations; recompute them in backward.
    TPU-native: this IS ``jax.checkpoint`` (XLA rematerialization);
    ``use_reentrant``/``preserve_rng_state`` knobs are meaningless under
    functional RNG and accepted for signature parity."""
    kwargs.pop("use_reentrant", None)
    kwargs.pop("preserve_rng_state", None)
    return jax.checkpoint(function)(*args, **kwargs)


def group_sharded_parallel(model, optimizer, level="p_g_os", scaler=None,
                           **kw):
    """Parity: paddle.distributed.sharding.group_sharded_parallel.

    level: "os" (ZeRO-1: optimizer state), "os_g" (ZeRO-2: +grads),
    "p_g_os" (ZeRO-3: +params). The reference wraps model/optimizer in
    GroupSharded* classes; here sharding is a property of the compiled
    program, so this returns (model, optimizer, strategy) — hand the
    strategy to ``TrainStep`` (or ``fleet.distributed_model``), which
    emits the partition specs the level implies. ``scaler`` passes
    through untouched (bf16 needs no loss scaling on TPU)."""
    from .strategy import DistributedStrategy

    stage = {"os": 1, "os_g": 2, "p_g_os": 3}.get(level)
    if stage is None:
        raise ValueError(
            f"unknown group_sharded level {level!r}; one of os/os_g/p_g_os")
    strategy = DistributedStrategy()
    strategy.sharding = True
    strategy.sharding_configs.stage = stage
    # fixed arity regardless of scaler — a conditional return shape is a
    # porting trap (scaler is None when not supplied)
    return model, optimizer, strategy, scaler
