"""DistributedStrategy (parity: paddle.distributed.fleet.DistributedStrategy,
backed upstream by paddle/fluid/framework/distributed_strategy.proto).

A serializable dataclass holding every distributed knob. The axis order of
the hybrid mesh follows Fleet's HybridCommunicateGroup convention
[dp, pp, sharding, sep, mp] (fleet/base/topology.py) — outermost axes get
the slowest-varying device stride, which on TPU maps dp/pp across hosts
(DCN) and sharding/sep/mp within a slice (ICI), the layout that keeps
high-traffic collectives on ICI.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional


@dataclasses.dataclass
class HybridConfig:
    dp_degree: int = 1
    mp_degree: int = 1  # tensor parallel
    pp_degree: int = 1  # pipeline parallel
    sharding_degree: int = 1  # ZeRO/FSDP axis
    sep_degree: int = 1  # Ulysses-style sequence/segment parallel
    ep_degree: int = 1  # expert parallel (MoE) — dedicated "ep" mesh axis,
    # composable with fsdp (EP×FSDP)
    cp_degree: int = 1  # ring-attention context parallel (alias onto sep axis
    # when both requested is unsupported)

    def total(self) -> int:
        return (
            self.dp_degree
            * self.mp_degree
            * self.pp_degree
            * self.sharding_degree
            * self.ep_degree
            * self.sep_degree
            * self.cp_degree
        )


@dataclasses.dataclass
class ShardingConfig:
    """Parity: DistributedStrategy.sharding_configs."""

    stage: int = 1  # 1: opt states, 2: +grads, 3: +params
    degree: int = 8
    offload: bool = False
    comm_overlap: bool = True


@dataclasses.dataclass
class RecomputeConfig:
    enable: bool = False
    # jax.checkpoint policy name: "full", "dots_saveable",
    # "nothing_saveable", "dots_with_no_batch_dims_saveable"
    policy: str = "dots_with_no_batch_dims_saveable"
    checkpoint_layers: Optional[list] = None


@dataclasses.dataclass
class AmpConfig:
    enable: bool = False
    dtype: str = "bfloat16"
    level: str = "O2"
    init_loss_scaling: float = 32768.0
    use_dynamic_loss_scaling: bool = False  # bf16: off


@dataclasses.dataclass
class PipelineConfig:
    micro_batch_size: int = 1
    accumulate_steps: int = 1
    schedule_mode: str = "1F1B"  # or "F-then-B", "VPP"
    vpp_degree: int = 1


@dataclasses.dataclass
class MoEConfig:
    num_experts: int = 1
    top_k: int = 2
    capacity_factor: float = 1.25
    gate: str = "gshard"  # gshard | switch | naive
    aux_loss_weight: float = 0.01


@dataclasses.dataclass
class DistributedStrategy:
    hybrid_configs: HybridConfig = dataclasses.field(default_factory=HybridConfig)
    sharding_configs: ShardingConfig = dataclasses.field(default_factory=ShardingConfig)
    recompute_configs: RecomputeConfig = dataclasses.field(default_factory=RecomputeConfig)
    amp_configs: AmpConfig = dataclasses.field(default_factory=AmpConfig)
    pipeline_configs: PipelineConfig = dataclasses.field(default_factory=PipelineConfig)
    moe_configs: MoEConfig = dataclasses.field(default_factory=MoEConfig)
    sharding: bool = False
    recompute: bool = False
    amp: bool = False
    pipeline: bool = False
    gradient_merge: bool = False
    gradient_merge_k_steps: int = 1
    find_unused_parameters: bool = False
    fuse_grad_size_in_MB: int = 32  # parity knob; XLA fuses regardless

    # ------------------------------------------------------------------
    def serialize(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def deserialize(cls, text: str) -> "DistributedStrategy":
        raw = json.loads(text)

        def build(klass, d):
            fields = {f.name: f for f in dataclasses.fields(klass)}
            kwargs = {}
            for k, val in d.items():
                if k not in fields:
                    continue
                ft = fields[k].type
                sub = {
                    "HybridConfig": HybridConfig,
                    "ShardingConfig": ShardingConfig,
                    "RecomputeConfig": RecomputeConfig,
                    "AmpConfig": AmpConfig,
                    "PipelineConfig": PipelineConfig,
                    "MoEConfig": MoEConfig,
                }.get(ft if isinstance(ft, str) else getattr(ft, "__name__", ""))
                kwargs[k] = build(sub, val) if sub and isinstance(val, dict) else val
            return klass(**kwargs)

        return build(cls, raw)

    # convenience used throughout the sharding engine
    @property
    def tp(self) -> int:
        return self.hybrid_configs.mp_degree

    @property
    def dp(self) -> int:
        return self.hybrid_configs.dp_degree

    @property
    def pp(self) -> int:
        return self.hybrid_configs.pp_degree

    @property
    def fsdp(self) -> int:
        return self.hybrid_configs.sharding_degree

    @property
    def sep(self) -> int:
        return self.hybrid_configs.sep_degree

    @property
    def sharding_stage(self) -> int:
        return self.sharding_configs.stage if (
            self.sharding or self.hybrid_configs.sharding_degree > 1
        ) else 0
