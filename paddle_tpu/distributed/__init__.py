"""paddle_tpu.distributed — the hybrid-parallel engine.

Parity: python/paddle/distributed/ (fleet, collective API, auto_parallel,
launch) re-expressed as mesh + GSPMD shardings (see SURVEY.md §5
"Distributed communication backend" for the mapping rationale).
"""

from . import parallel_layers  # noqa: F401
from .auto_parallel import (  # noqa: F401
    Partial,
    Placement,
    ProcessMesh,
    Replicate,
    Shard,
    dtensor_from_fn,
    get_mesh,
    get_placements,
    reshard,
    set_mesh,
    shard_layer,
    shard_optimizer,
    shard_tensor,
)
from .collective import (  # noqa: F401
    Group,
    P2POp,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    alltoall,
    alltoall_single,
    barrier,
    batch_isend_irecv,
    broadcast,
    broadcast_object_list,
    destroy_process_group,
    gather,
    get_group,
    irecv,
    is_initialized,
    isend,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    wait,
)
from . import checkpoint  # noqa: F401
from .env import (  # noqa: F401
    device_count,
    get_local_rank,
    get_rank,
    get_world_size,
    init_parallel_env,
    local_device_count,
)
from .moe import MoELayer  # noqa: F401
from .parallel_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from .sharding import (  # noqa: F401
    batch_spec,
    model_shardings,
    opt_state_shardings,
    param_partition_spec,
    place_params_on_mesh,
    sequence_parallel_constraint,
    shard_activation,
    group_sharded_parallel,
    recompute,
)
from .strategy import (  # noqa: F401
    AmpConfig,
    DistributedStrategy,
    HybridConfig,
    MoEConfig,
    PipelineConfig,
    RecomputeConfig,
    ShardingConfig,
)
from .topology import (  # noqa: F401
    HybridCommunicateGroup,
    build_mesh,
    fleet_init,
    get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)

# ---- round-5 surface sweep ----
from . import fleet  # noqa: F401,E402
from . import stream  # noqa: F401,E402
from . import launch  # noqa: F401,E402
from .collective import alltoall as all_to_all  # noqa: F401,E402
from .auto_parallel import (  # noqa: F401,E402
    dtensor_to_local,
    parallelize,
    unshard_dtensor,
)
from .env import ParallelEnv, spawn  # noqa: F401,E402
