"""Process-level distributed environment.

Parity: paddle.distributed.init_parallel_env / get_rank / get_world_size
(python/paddle/distributed/parallel.py) and the C++ TCPStore rendezvous
(paddle/phi/core/distributed/store/tcp_store.cc).

TPU-native: ``jax.distributed.initialize`` provides the coordination
service (its coordinator IS the TCP store) and device visibility across
hosts; per-tensor traffic never touches it. Single-process multi-device
(one host, 4–8 TPU chips, or a CPU mesh in tests) needs no init at all.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def init_parallel_env(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize multi-host JAX. Env parity: PADDLE_MASTER /
    PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID (set by the launch CLI) are
    honored alongside the standard JAX coordinator variables."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "PADDLE_MASTER"
    ) or os.environ.get("COORDINATOR_ADDRESS")
    num_processes = num_processes or int(
        os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("NPROC", "1"))
    )
    process_id = process_id if process_id is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", os.environ.get("PROC_ID", "0"))
    )
    if num_processes > 1 and coordinator_address:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    _initialized = True


def get_rank() -> int:
    return jax.process_index()


def get_world_size() -> int:
    return jax.process_count()


def get_local_rank() -> int:
    return int(os.environ.get("PADDLE_LOCAL_RANK", "0"))


def is_initialized() -> bool:
    return _initialized


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


class ParallelEnv:
    """Parity: paddle.distributed.ParallelEnv — rank/world/device
    queries as attributes (legacy dygraph DDP surface)."""

    @property
    def rank(self) -> int:
        return get_rank()

    @property
    def world_size(self) -> int:
        return get_world_size()

    @property
    def device_id(self) -> int:
        return get_local_rank()

    @property
    def dev_id(self) -> int:  # legacy spelling
        return get_local_rank()

    @property
    def nranks(self) -> int:  # legacy spelling
        return get_world_size()

    @property
    def local_rank(self) -> int:
        return get_rank()


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Parity: paddle.distributed.spawn — launch ``func`` in ``nprocs``
    OS processes with PADDLE_* rank env set, as the launch CLI does.
    On TPU real multi-host runs go through ``paddle_tpu.distributed.
    launch`` (one process per host; chips are one process's devices),
    so spawn is for host-side parallelism and CPU-mesh tests."""
    import multiprocessing as mp

    if nprocs <= 0:
        nprocs = max(1, local_device_count())
    # pick a free coordinator port BEFORE forking (paddle's spawn does
    # the same): without PADDLE_MASTER, a worker's init_parallel_env
    # would skip jax.distributed.initialize and every worker would run
    # as an independent rank-0 world
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        master = f"127.0.0.1:{s.getsockname()[1]}"
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {"PADDLE_TRAINER_ID": str(rank),
               "PADDLE_TRAINERS_NUM": str(nprocs),
               "PADDLE_LOCAL_RANK": str(rank),
               "PADDLE_MASTER": master}
        p = ctx.Process(target=_spawn_entry, args=(func, args, env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        bad = [p.exitcode for p in procs if p.exitcode]
        if bad:
            raise RuntimeError(f"spawn: worker exit codes {bad}")
    return procs


def _spawn_entry(func, args, env):
    os.environ.update(env)
    func(*args)
