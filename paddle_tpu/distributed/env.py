"""Process-level distributed environment.

Parity: paddle.distributed.init_parallel_env / get_rank / get_world_size
(python/paddle/distributed/parallel.py) and the C++ TCPStore rendezvous
(paddle/phi/core/distributed/store/tcp_store.cc).

TPU-native: ``jax.distributed.initialize`` provides the coordination
service (its coordinator IS the TCP store) and device visibility across
hosts; per-tensor traffic never touches it. Single-process multi-device
(one host, 4–8 TPU chips, or a CPU mesh in tests) needs no init at all.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def init_parallel_env(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize multi-host JAX. Env parity: PADDLE_MASTER /
    PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID (set by the launch CLI) are
    honored alongside the standard JAX coordinator variables."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "PADDLE_MASTER"
    ) or os.environ.get("COORDINATOR_ADDRESS")
    num_processes = num_processes or int(
        os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("NPROC", "1"))
    )
    process_id = process_id if process_id is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", os.environ.get("PROC_ID", "0"))
    )
    if num_processes > 1 and coordinator_address:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    _initialized = True


def get_rank() -> int:
    return jax.process_index()


def get_world_size() -> int:
    return jax.process_count()


def get_local_rank() -> int:
    return int(os.environ.get("PADDLE_LOCAL_RANK", "0"))


def is_initialized() -> bool:
    return _initialized


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()
