"""Tensor-parallel layers.

Parity: fleet/meta_parallel/parallel_layers/mp_layers.py —
VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
ParallelCrossEntropy — and mp_ops.py's identity/allreduce autograd ops.

TPU-native inversion: the reference materializes *local* shards
([in, out/tp] weights) and calls collectives by hand (allreduce in row
forward, identity/allreduce pairs for backward). Here every layer keeps
the *global* logical shape and only annotates ``Parameter.spec``; GSPMD
partitions the matmul and inserts the exact same collectives (it derives
the allreduce a row-parallel matmul needs from the contracted-dim
sharding). ``gather_output`` / ``input_is_parallel`` become activation
sharding constraints.

This is why there is no mp_ops.py here: `_c_identity`/`_c_allreduce`
pairs are compiler output, not user code.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ...core import initializer as I
from ...core.module import Layer
from ...nn import functional as F
from ..sharding import shard_activation


class ColumnParallelLinear(Layer):
    """Weight [in, out] with the out dim sharded over "tp".

    gather_output=False leaves activations sharded over tp (feeding a
    RowParallelLinear); True constrains the output replicated.
    """

    def __init__(
        self,
        in_features: int,
        out_features: int,
        weight_attr=None,
        has_bias: bool = True,
        gather_output: bool = False,
        fuse_matmul_bias: bool = False,
        name=None,
    ):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features),
            default_initializer=weight_attr,
            spec=(None, "tp"),
        )
        self.weight.is_distributed = True
        if has_bias:
            self.bias = self.create_parameter(
                (out_features,), is_bias=True, spec=("tp",)
            )
            self.bias.is_distributed = True
        else:
            self.bias = None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            y = shard_activation(y, ("dp", "fsdp"), *([None] * (y.ndim - 1)))
        else:
            y = shard_activation(
                y, ("dp", "fsdp"), *([None] * (y.ndim - 2)), "tp"
            )
        return y


class RowParallelLinear(Layer):
    """Weight [in, out] with the in (contracted) dim sharded over "tp" —
    GSPMD emits the partial-sum allreduce the reference codes by hand."""

    def __init__(
        self,
        in_features: int,
        out_features: int,
        weight_attr=None,
        has_bias: bool = True,
        input_is_parallel: bool = True,
        fuse_matmul_bias: bool = False,
        name=None,
    ):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features),
            default_initializer=weight_attr,
            spec=("tp", None),
        )
        self.weight.is_distributed = True
        if has_bias:
            # bias is applied after the reduce → replicated (parity: row
            # linear adds bias on the full output)
            self.bias = self.create_parameter((out_features,), is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = shard_activation(
                x, ("dp", "fsdp"), *([None] * (x.ndim - 2)), "tp"
            )
        y = F.linear(x, self.weight, None)
        y = shard_activation(y, ("dp", "fsdp"), *([None] * (y.ndim - 1)))
        if self.bias is not None:
            y = y + self.bias.value
        return y


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over "tp"."""

    def __init__(
        self,
        num_embeddings: int,
        embedding_dim: int,
        weight_attr=None,
        name=None,
    ):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim),
            default_initializer=weight_attr or I.Normal(0.0, 0.02),
            spec=("tp", None),
        )
        self.weight.is_distributed = True

    def forward(self, x):
        # Constrain the weight's hidden dim replicated before the gather:
        # under ZeRO-3 fsdp lands on the hidden dim (vocab is taken by
        # tp), and a gather from a hidden-sharded table produces
        # hidden-sharded activations that SPMD can only reshard to the
        # batch/seq layout by full rematerialization. Forcing the
        # all-gather onto the weight (the ZeRO-3 contract anyway) keeps
        # the gather output partitionable along batch/seq.
        w = shard_activation(self.weight.value, "tp", None)
        y = F.embedding(x, w)
        return shard_activation(y, ("dp", "fsdp"), *([None] * (y.ndim - 2)), None)


class ParallelCrossEntropy(Layer):
    """Cross entropy over tp-sharded logits (parity:
    mp_ops._c_softmax_with_cross_entropy): constrain the vocab dim sharded
    so the softmax reductions become tp-axis collectives instead of a
    logits all-gather."""

    def __init__(self, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, label):
        logits = shard_activation(
            logits, ("dp", "fsdp"), *([None] * (logits.ndim - 2)), "tp"
        )
        return F.cross_entropy(
            logits, label, ignore_index=self.ignore_index, reduction="none"
        )
