"""Semi-auto parallel API.

Parity: python/paddle/distributed/auto_parallel/ — ``ProcessMesh``,
``shard_tensor(t, mesh, [Shard(0), Replicate()])``, placements
(Shard/Replicate/Partial), ``reshard``, ``shard_layer``.

TPU-native: placements translate 1:1 to PartitionSpec entries and
``jax.device_put`` / ``with_sharding_constraint``; the reference's whole
static pipeline — Completion (SPMD-rule propagation through every op,
phi/infermeta/spmd_rules/), Planner, Partitioner (per-rank program
cloning), and reshard-insertion (static/reshard.py) — is exactly what
GSPMD performs inside XLA when it propagates these annotations, so none
of it is reimplemented here.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.module import Layer
from ..core.parameter import Parameter


class Placement:
    pass


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)


class Partial(Placement):
    """Pending-reduction placement. GSPMD tracks partial sums internally;
    at the API boundary a Partial input is materialized by reducing, so
    ``reshard`` from Partial is the psum the reference's P→R function
    runs."""

    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"


class ProcessMesh:
    """Parity: paddle.distributed.ProcessMesh(mesh, dim_names)."""

    def __init__(self, mesh, dim_names: Optional[Sequence[str]] = None,
                 devices=None):
        arr = np.asarray(mesh)
        self.shape = arr.shape
        self.process_ids = arr.flatten().tolist()
        self.dim_names = list(dim_names or [f"d{i}" for i in range(arr.ndim)])
        if devices is None:
            devices = jax.devices()
        dev_arr = np.array([devices[i] for i in self.process_ids]).reshape(
            self.shape
        )
        self.jax_mesh = Mesh(dev_arr, tuple(self.dim_names))

    @property
    def ndim(self):
        return len(self.shape)

    def get_dim_size(self, name: str):
        return self.shape[self.dim_names.index(name)]

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def _placements_to_spec(placements: List[Placement], mesh: ProcessMesh,
                        ndim: int) -> P:
    """placements[i] says how mesh dim i maps onto tensor dims."""
    entries: List = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            axis = mesh.dim_names[mesh_dim]
            cur = entries[pl.dim]
            if cur is None:
                entries[pl.dim] = axis
            elif isinstance(cur, tuple):
                entries[pl.dim] = cur + (axis,)
            else:
                entries[pl.dim] = (cur, axis)
        # Replicate/Partial → no entry
    return P(*entries)


def shard_tensor(x, mesh: ProcessMesh, placements: List[Placement],
                 stop_gradient: bool = None):
    """Place a tensor (or Parameter) on the mesh with the given placements.

    Inside a traced computation this lowers to a sharding constraint;
    eagerly it device_puts to a NamedSharding.
    """
    if isinstance(x, Parameter):
        spec = _placements_to_spec(placements, mesh, x.value.ndim)
        x.spec = tuple(spec)
        x.value = jax.device_put(
            x.value, NamedSharding(mesh.jax_mesh, spec)
        )
        return x
    arr = x
    spec = _placements_to_spec(placements, mesh, arr.ndim)
    if isinstance(arr, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(arr, spec)
    return jax.device_put(arr, NamedSharding(mesh.jax_mesh, spec))


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(x, mesh: ProcessMesh, placements: List[Placement]):
    """Parity: paddle.distributed.reshard — move a distributed tensor to a
    new placement; every S→R / R→S / P→R / cross-mesh case in the
    reference's ReshardFunction hierarchy (phi/core/distributed/
    auto_parallel/reshard/) reduces to one device_put / constraint here."""
    spec = _placements_to_spec(placements, mesh, x.ndim)
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, spec)
    return jax.device_put(x, NamedSharding(mesh.jax_mesh, spec))


def shard_layer(
    layer: Layer,
    process_mesh: ProcessMesh,
    shard_fn=None,
    input_fn=None,
    output_fn=None,
) -> Layer:
    """Parity: dist.shard_layer — apply shard_fn(sublayer_name, sublayer,
    mesh) over the tree to annotate parameters."""
    if shard_fn is None:
        # default: replicate everything on the mesh
        def shard_fn(name, sub, mesh):
            for _, p in sub.named_parameters(include_sublayers=False):
                shard_tensor(p, mesh, [Replicate()] * mesh.ndim)

    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, args: input_fn(args, process_mesh)
        )
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, args, out: output_fn(out, process_mesh)
        )
    return layer


def get_placements(x, mesh: ProcessMesh):
    """Inverse query: derive placements of an array on the given mesh."""
    sharding = getattr(x, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return [Replicate() for _ in mesh.dim_names]
    spec = sharding.spec
    placements: List[Placement] = [Replicate() for _ in mesh.dim_names]
    for tensor_dim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            placements[mesh.dim_names.index(ax)] = Shard(tensor_dim)
    return placements


_GLOBAL_MESH = [None]


def set_mesh(mesh):
    """Parity: paddle.distributed.set_mesh — record the global
    ProcessMesh used by the auto-parallel APIs."""
    _GLOBAL_MESH[0] = mesh
    return mesh


def get_mesh():
    """Parity: paddle.distributed.get_mesh."""
    return _GLOBAL_MESH[0]


def shard_optimizer(optimizer, shard_fn=None):
    """Parity: paddle.distributed.shard_optimizer.

    The reference walks optimizer state dicts and re-places each slot
    on the mesh; here optimizer slots are created with
    ``zeros_like(param)`` inside the jitted step, so GSPMD gives every
    slot its parameter's sharding automatically — exactly the placement
    ``shard_fn`` (e.g. ShardOptimizer stage-3) would assign. The wrapper
    exists for call-site parity and applies ``shard_fn`` to any
    already-materialized state."""
    if shard_fn is not None and hasattr(optimizer, "_state"):
        optimizer._state = shard_fn(optimizer._state)
    return optimizer


def dtensor_to_local(x, mesh=None, placements=None):
    """Parity: dist.dtensor_to_local — this process's addressable part
    as a plain array. Replicated arrays return one copy; in a
    single-process world every shard is addressable, so the local form
    IS the global array; a multi-host shard set is reassembled along
    its sharded axes from each shard's global index."""
    shards = getattr(x, "addressable_shards", None)
    if not shards:
        return x
    if len(shards) == 1:
        return shards[0].data
    import jax

    if getattr(x.sharding, "is_fully_replicated", False):
        return shards[0].data
    if len(shards) == len(x.sharding.device_set):
        # single-process: all shards addressable -> local == global
        return x
    # multi-host: paste each addressable shard into the bounding box of
    # the addressable region using its global index
    import numpy as np

    idxs = [s.index for s in shards]
    starts = [min(ix[d].start or 0 for ix in idxs)
              for d in range(x.ndim)]
    stops = [max(ix[d].stop if ix[d].stop is not None else x.shape[d]
                 for ix in idxs) for d in range(x.ndim)]
    out = np.zeros([b - a for a, b in zip(starts, stops)], x.dtype)
    for s in shards:
        sl = tuple(slice((ix.start or 0) - a,
                         ((ix.stop if ix.stop is not None else dim)) - a)
                   for ix, a, dim in zip(s.index, starts, x.shape))
        out[sl] = np.asarray(s.data)
    return jax.numpy.asarray(out)


def unshard_dtensor(x):
    """Parity: dist.unshard_dtensor — replicate across the array's own
    mesh. Sharded-on-a-mesh inputs get an explicit fully-replicated
    NamedSharding (XLA inserts the all-gather); plain single-device
    arrays pass through. Multi-host non-addressable arrays must be
    gathered by the caller's collective (jax forbids implicit cross-host
    device_get)."""
    import jax

    sharding = getattr(x, "sharding", None)
    mesh = getattr(sharding, "mesh", None)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(x, NamedSharding(mesh, PartitionSpec()))
    # mesh-less shardings (GSPMDSharding from deserialized executables,
    # PositionalSharding): replicate over the SAME device set
    if sharding is None or len(getattr(sharding, "device_set", ())) <= 1:
        return x
    if getattr(x, "is_fully_addressable", True):
        # replicate over the SAME device set via a throwaway 1-axis
        # mesh (PositionalSharding no longer exists in current jax)
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        devs = np.array(sorted(sharding.device_set, key=lambda d: d.id))
        repl = NamedSharding(Mesh(devs, ("_unshard",)), PartitionSpec())
        return jax.device_put(x, repl)
    return x


def parallelize(model, optimizer=None, mesh=None, config=None):
    """Parity: paddle.distributed.parallelize (the 3.0 one-call API:
    apply a parallel config to model+optimizer). Sharding here is
    declared on Parameters (`.spec`) and consumed by TrainStep over the
    active mesh, so the pair passes through; ``config`` dicts naming
    dp/mp/pp degrees should instead build a DistributedStrategy (see
    distributed.strategy) — raising on unknown keys would break the
    reference's permissive contract, so unknown configs are ignored."""
    if optimizer is None:
        return model
    return model, optimizer
