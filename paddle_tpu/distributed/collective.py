"""Collective communication API.

Parity: python/paddle/distributed/communication/ (all_reduce, all_gather,
reduce_scatter, alltoall, broadcast, send/recv, barrier) over
ProcessGroupNCCL (paddle/fluid/distributed/collective/).

TPU-native: there is no userspace NCCL to wrap. Tensor-traffic
collectives are XLA HLO ops emitted *inside* compiled programs — either
implicitly by GSPMD or explicitly via ``jax.lax.p*`` under ``shard_map``.
This module provides:
  1. in-jit functions (psum/all_gather/...) usable inside shard_map'ed
     code, matching paddle.distributed call signatures; and
  2. eager wrappers that shard_map a single collective over the active
     mesh — the moral equivalent of a one-op NCCL launch, used by tests
     and host-side logic (and by checkpoint barriers).
Host-level coordination (the reference's TCPStore) is
``jax.distributed``'s builtin store; see env.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from .topology import get_hybrid_communicate_group


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


# ---------------------------------------------------------------------------
# in-jit collectives (call inside shard_map with a named axis)
# ---------------------------------------------------------------------------
def all_reduce_in(x, op: str = ReduceOp.SUM, axis: str = "dp"):
    if op == ReduceOp.SUM:
        return jax.lax.psum(x, axis)
    if op == ReduceOp.MAX:
        return jax.lax.pmax(x, axis)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(x, axis)
    if op == ReduceOp.AVG:
        return jax.lax.pmean(x, axis)
    if op == ReduceOp.PROD:
        return jnp.exp(jax.lax.psum(jnp.log(x), axis))
    raise ValueError(op)


def all_gather_in(x, axis: str = "dp", tiled_dim: int = 0):
    return jax.lax.all_gather(x, axis, axis=tiled_dim, tiled=True)


def reduce_scatter_in(x, axis: str = "dp", scatter_dim: int = 0):
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_dim,
                                tiled=True)


def all_to_all_in(x, axis: str = "sep", split_dim: int = 0, concat_dim: int = 0):
    return jax.lax.all_to_all(x, axis, split_axis=split_dim,
                              concat_axis=concat_dim, tiled=True)


def ppermute_in(x, axis: str, perm):
    return jax.lax.ppermute(x, axis, perm)


def axis_index(axis: str):
    return jax.lax.axis_index(axis)


# ---------------------------------------------------------------------------
# eager wrappers over the active mesh
# ---------------------------------------------------------------------------
def _active_mesh() -> Mesh:
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        raise RuntimeError(
            "no active mesh: call distributed.init_parallel_env() / "
            "fleet_init first"
        )
    return hcg.mesh


def _group_axis(group) -> str:
    if group is None:
        return "dp"
    if isinstance(group, str):
        return group
    return group.axis


def all_reduce(tensor, op=ReduceOp.SUM, group=None, mesh: Optional[Mesh] = None):
    """Eager allreduce over one mesh axis. The input is interpreted as
    *already sharded* along that axis (dim 0 carries the per-rank data in
    the reference's SPMD model)."""
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)
    other = tuple(a for a in mesh.axis_names if a != axis)
    spec = P(axis)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=spec, out_specs=spec,
        check_vma=False,
    )
    def f(x):
        return all_reduce_in(x, op, axis)

    return f(tensor)


def all_gather(tensor_or_list, tensor=None, group=None, mesh=None):
    """paddle signature: all_gather(out_list, tensor). Returns the list of
    per-rank pieces; also supports functional use all_gather(tensor)."""
    if isinstance(tensor_or_list, list):
        out_list, x = tensor_or_list, tensor
    else:
        out_list, x = None, tensor_or_list
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)
    n = mesh.shape[axis]

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    def f(xs):
        return all_gather_in(xs, axis, 0)

    stacked = f(x)
    if out_list is not None:
        per = stacked.shape[0] // n
        chunks = [stacked[i * per:(i + 1) * per] for i in range(n)]
        out_list.extend(chunks)
        return out_list
    return stacked


def reduce_scatter(tensor, group=None, op=ReduceOp.SUM, mesh=None):
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    def f(x):
        return reduce_scatter_in(x, axis, 0)

    return f(tensor)


def alltoall(in_tensor_list, out_tensor_list=None, group=None, mesh=None):
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)
    x = (
        jnp.concatenate(in_tensor_list, axis=0)
        if isinstance(in_tensor_list, (list, tuple))
        else in_tensor_list
    )

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    def f(x):
        return all_to_all_in(x, axis, 0, 0)

    out = f(x)
    if out_tensor_list is not None:
        n = mesh.shape[axis]
        per = out.shape[0] // n
        out_tensor_list.extend(
            out[i * per:(i + 1) * per] for i in range(n)
        )
        return out_tensor_list
    return out


def broadcast(tensor, src: int = 0, group=None, mesh=None):
    """Replicate src rank's shard to all ranks along the axis."""
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)
    n = mesh.shape[axis]

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    def f(x):
        full = all_gather_in(x, axis, 0)
        per = full.shape[0] // n
        piece = jax.lax.dynamic_slice_in_dim(full, src * per, per, 0)
        return piece

    return f(tensor)


def barrier(group=None):
    """Host barrier: a trivial device allreduce forces synchronization."""
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return
    x = jnp.ones((hcg.mesh.devices.size,), jnp.int32)
    all_reduce(x, mesh=hcg.mesh, group="dp") if "dp" in hcg.mesh.axis_names \
        else None


# ---------------------------------------------------------------------------
# object collectives (parity: paddle.distributed.all_gather_object /
# broadcast_object_list — pickled python objects over the coordination
# service rather than NCCL byte tensors)
# ---------------------------------------------------------------------------
def _object_via_host(obj, tag: str):
    """Share pickled objects through jax's multihost broadcast (the
    TPU-world TCPStore): every process contributes, all receive the
    list ordered by process index."""
    import pickle

    import numpy as np

    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    # fixed-size frame: length-prefix + padded body, gathered as one
    # host-value broadcast per process
    max_len = int(multihost_utils.process_allgather(
        jnp.asarray([payload.size]))[..., 0].max())
    frame = np.zeros((max_len + 8,), np.uint8)
    frame[:8] = np.frombuffer(
        np.asarray([payload.size], np.int64).tobytes(), np.uint8)
    frame[8:8 + payload.size] = payload
    gathered = np.asarray(
        multihost_utils.process_allgather(jnp.asarray(frame)))
    out = []
    for row in gathered.reshape(jax.process_count(), -1):
        n = int(np.frombuffer(row[:8].tobytes(), np.int64)[0])
        out.append(pickle.loads(row[8:8 + n].tobytes()))
    return out


def all_gather_object(object_list, obj, group=None):
    """Parity: paddle.distributed.all_gather_object — appends every
    rank's ``obj`` (any picklable) into ``object_list``."""
    object_list.extend(_object_via_host(obj, "all_gather_object"))
    return object_list


def broadcast_object_list(object_list, src: int = 0, group=None):
    """Parity: paddle.distributed.broadcast_object_list — replaces the
    list contents with rank ``src``'s."""
    gathered = _object_via_host(list(object_list), "broadcast_object")
    if not 0 <= src < len(gathered):
        raise ValueError(
            f"broadcast_object_list: src {src} out of range for "
            f"{len(gathered)} process(es)")
    object_list[:] = gathered[src]
    return object_list
