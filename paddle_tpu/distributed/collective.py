"""Collective communication API.

Parity: python/paddle/distributed/communication/ (all_reduce, all_gather,
reduce_scatter, alltoall, broadcast, send/recv, barrier) over
ProcessGroupNCCL (paddle/fluid/distributed/collective/).

TPU-native: there is no userspace NCCL to wrap. Tensor-traffic
collectives are XLA HLO ops emitted *inside* compiled programs — either
implicitly by GSPMD or explicitly via ``jax.lax.p*`` under ``shard_map``.
This module provides:
  1. in-jit functions (psum/all_gather/...) usable inside shard_map'ed
     code, matching paddle.distributed call signatures; and
  2. eager wrappers that shard_map a single collective over the active
     mesh — the moral equivalent of a one-op NCCL launch, used by tests
     and host-side logic (and by checkpoint barriers).
Host-level coordination (the reference's TCPStore) is
``jax.distributed``'s builtin store; see env.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..jax_compat import shard_map

from ..observability import record_collective as _record
from .topology import get_hybrid_communicate_group


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


# ---------------------------------------------------------------------------
# in-jit collectives (call inside shard_map with a named axis).
# Each records (op, axis, payload bytes, call site) at TRACE time via
# observability.comm — one entry per collective baked into a compiled
# program, so a program's communication volume is queryable.
# ---------------------------------------------------------------------------
def all_reduce_in(x, op: str = ReduceOp.SUM, axis: str = "dp"):
    _record("all_reduce", axis, x)
    if op == ReduceOp.SUM:
        return jax.lax.psum(x, axis)
    if op == ReduceOp.MAX:
        return jax.lax.pmax(x, axis)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(x, axis)
    if op == ReduceOp.AVG:
        return jax.lax.pmean(x, axis)
    if op == ReduceOp.PROD:
        return jnp.exp(jax.lax.psum(jnp.log(x), axis))
    raise ValueError(op)


def all_gather_in(x, axis: str = "dp", tiled_dim: int = 0):
    _record("all_gather", axis, x)
    return jax.lax.all_gather(x, axis, axis=tiled_dim, tiled=True)


def reduce_scatter_in(x, axis: str = "dp", scatter_dim: int = 0):
    _record("reduce_scatter", axis, x)
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_dim,
                                tiled=True)


def all_to_all_in(x, axis: str = "sep", split_dim: int = 0, concat_dim: int = 0):
    _record("all_to_all", axis, x)
    return jax.lax.all_to_all(x, axis, split_axis=split_dim,
                              concat_axis=concat_dim, tiled=True)


def ppermute_in(x, axis: str, perm):
    _record("ppermute", axis, x)
    return jax.lax.ppermute(x, axis, perm)


def axis_index(axis: str):
    return jax.lax.axis_index(axis)


# ---------------------------------------------------------------------------
# eager wrappers over the active mesh
# ---------------------------------------------------------------------------
def _active_mesh() -> Mesh:
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        raise RuntimeError(
            "no active mesh: call distributed.init_parallel_env() / "
            "fleet_init first"
        )
    return hcg.mesh


def _group_axis(group) -> str:
    if group is None:
        return "dp"
    if isinstance(group, str):
        return group
    return group.axis


def all_reduce(tensor, op=ReduceOp.SUM, group=None, mesh: Optional[Mesh] = None):
    """Eager allreduce over one mesh axis. The input is interpreted as
    *already sharded* along that axis (dim 0 carries the per-rank data in
    the reference's SPMD model)."""
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)
    other = tuple(a for a in mesh.axis_names if a != axis)
    spec = P(axis)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=spec, out_specs=spec,
        check_vma=False,
    )
    def f(x):
        return all_reduce_in(x, op, axis)

    return f(tensor)


def all_gather(tensor_or_list, tensor=None, group=None, mesh=None):
    """paddle signature: all_gather(out_list, tensor). Returns the list of
    per-rank pieces; also supports functional use all_gather(tensor)."""
    if isinstance(tensor_or_list, list):
        out_list, x = tensor_or_list, tensor
    else:
        out_list, x = None, tensor_or_list
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)
    n = mesh.shape[axis]

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    def f(xs):
        return all_gather_in(xs, axis, 0)

    stacked = f(x)
    if out_list is not None:
        per = stacked.shape[0] // n
        chunks = [stacked[i * per:(i + 1) * per] for i in range(n)]
        out_list.extend(chunks)
        return out_list
    return stacked


def reduce_scatter(tensor, group=None, op=ReduceOp.SUM, mesh=None):
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    def f(x):
        return reduce_scatter_in(x, axis, 0)

    return f(tensor)


def alltoall(in_tensor_list, out_tensor_list=None, group=None, mesh=None):
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)
    x = (
        jnp.concatenate(in_tensor_list, axis=0)
        if isinstance(in_tensor_list, (list, tuple))
        else in_tensor_list
    )

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    def f(x):
        return all_to_all_in(x, axis, 0, 0)

    out = f(x)
    if out_tensor_list is not None:
        n = mesh.shape[axis]
        per = out.shape[0] // n
        out_tensor_list.extend(
            out[i * per:(i + 1) * per] for i in range(n)
        )
        return out_tensor_list
    return out


def broadcast(tensor, src: int = 0, group=None, mesh=None):
    """Replicate src rank's shard to all ranks along the axis."""
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)
    n = mesh.shape[axis]

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    def f(x):
        full = all_gather_in(x, axis, 0)
        per = full.shape[0] // n
        piece = jax.lax.dynamic_slice_in_dim(full, src * per, per, 0)
        return piece

    return f(tensor)


def barrier(group=None):
    """Host barrier: a trivial device allreduce forces synchronization."""
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return
    x = jnp.ones((hcg.mesh.devices.size,), jnp.int32)
    all_reduce(x, mesh=hcg.mesh, group="dp") if "dp" in hcg.mesh.axis_names \
        else None


# ---------------------------------------------------------------------------
# object collectives (parity: paddle.distributed.all_gather_object /
# broadcast_object_list — pickled python objects over the coordination
# service rather than NCCL byte tensors)
# ---------------------------------------------------------------------------
def _object_via_host(obj, tag: str):
    """Share pickled objects through jax's multihost broadcast (the
    TPU-world TCPStore): every process contributes, all receive the
    list ordered by process index."""
    import pickle

    import numpy as np

    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(obj), np.uint8)
    # fixed-size frame: length-prefix + padded body, gathered as one
    # host-value broadcast per process
    max_len = int(multihost_utils.process_allgather(
        jnp.asarray([payload.size]))[..., 0].max())
    frame = np.zeros((max_len + 8,), np.uint8)
    frame[:8] = np.frombuffer(
        np.asarray([payload.size], np.int64).tobytes(), np.uint8)
    frame[8:8 + payload.size] = payload
    gathered = np.asarray(
        multihost_utils.process_allgather(jnp.asarray(frame)))
    out = []
    for row in gathered.reshape(jax.process_count(), -1):
        n = int(np.frombuffer(row[:8].tobytes(), np.int64)[0])
        out.append(pickle.loads(row[8:8 + n].tobytes()))
    return out


def all_gather_object(object_list, obj, group=None):
    """Parity: paddle.distributed.all_gather_object — appends every
    rank's ``obj`` (any picklable) into ``object_list``."""
    object_list.extend(_object_via_host(obj, "all_gather_object"))
    return object_list


def broadcast_object_list(object_list, src: int = 0, group=None):
    """Parity: paddle.distributed.broadcast_object_list — replaces the
    list contents with rank ``src``'s."""
    gathered = _object_via_host(list(object_list), "broadcast_object")
    if not 0 <= src < len(gathered):
        raise ValueError(
            f"broadcast_object_list: src {src} out of range for "
            f"{len(gathered)} process(es)")
    object_list[:] = gathered[src]
    return object_list


# ---------------------------------------------------------------------------
# groups (parity: paddle.distributed.new_group / Group). A TPU "group"
# is a mesh axis: arbitrary rank sets have no NCCL communicator to
# build — they must correspond to one axis's subgroups of the active
# mesh (the topology the reference's HCG builds its groups from too).
# ---------------------------------------------------------------------------
class Group:
    """A communicator handle bound to one mesh axis."""

    _registry: dict = {}
    _next_id = [1]

    def __init__(self, axis: str, ranks=None):
        self.axis = axis
        self.ranks = ranks
        self.id = Group._next_id[0]
        Group._next_id[0] += 1
        Group._registry[self.id] = self

    @property
    def nranks(self):
        return _active_mesh().shape[self.axis]

    def __repr__(self):
        return f"Group(axis={self.axis!r}, id={self.id})"


def _axis_subgroups(mesh: Mesh, axis: str):
    """Device-id rank sets forming each subgroup of ``axis``."""
    import numpy as np

    ax = mesh.axis_names.index(axis)
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    moved = np.moveaxis(ids, ax, -1).reshape(-1, ids.shape[ax])
    return [tuple(int(r) for r in row) for row in moved]


def new_group(ranks=None, backend=None, timeout=None, axis=None):
    """Create a Group. Pass ``axis=`` to bind a mesh axis directly, or
    ``ranks`` matching one of an axis's subgroups (the only rank sets a
    mesh topology can serve — anything else raises loudly)."""
    if axis is not None:
        return Group(axis, ranks)
    mesh = _active_mesh()
    if ranks is None:
        return Group(mesh.axis_names[0])
    want = tuple(int(r) for r in ranks)
    for ax in mesh.axis_names:
        if want in _axis_subgroups(mesh, ax):
            return Group(ax, want)
    raise ValueError(
        f"new_group(ranks={ranks}): rank set matches no mesh-axis "
        f"subgroup of {dict(mesh.shape)} — TPU groups are mesh axes")


def get_group(gid: int):
    return Group._registry.get(gid)


def destroy_process_group(group=None):
    if group is None:
        Group._registry.clear()
    else:
        Group._registry.pop(getattr(group, "id", None), None)


def is_initialized():
    return get_hybrid_communicate_group() is not None


# ---------------------------------------------------------------------------
# more eager collectives
# ---------------------------------------------------------------------------
def reduce(tensor, dst: int = 0, op=ReduceOp.SUM, group=None, mesh=None):
    """Reduce to rank ``dst``: every rank gets its own shard back except
    dst, which gets the reduction (SPMD lockstep form)."""
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    def f(x):
        red = all_reduce_in(x, op, axis)
        return jnp.where(jax.lax.axis_index(axis) == dst, red, x)

    return f(tensor)


def scatter(tensor, tensor_list=None, src: int = 0, group=None, mesh=None):
    """Rank r receives piece r of src's list (paddle signature:
    scatter(out, tensor_list, src))."""
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)
    n = mesh.shape[axis]
    x = (jnp.stack(tensor_list) if tensor_list is not None
         else tensor.reshape(n, -1, *tensor.shape[1:]))

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(), out_specs=P(axis),
        check_vma=False,
    )
    def f(full):
        i = jax.lax.axis_index(axis)
        return jax.lax.dynamic_index_in_dim(full, i, 0, keepdims=False)

    return f(x)


def gather(tensor, gather_list=None, dst: int = 0, group=None, mesh=None):
    """All ranks contribute their shard; the stacked result is returned
    (every rank materializes it — an SPMD program cannot hold rank-
    dependent shapes; paddle's dst-only contract is a subset)."""
    stacked = all_gather(tensor, group=group, mesh=mesh)
    n = (mesh or _active_mesh()).shape[_group_axis(group)]
    # the global result replicates the gathered block once per rank —
    # slice ONE block, then split it into the per-rank pieces
    gathered = stacked[: stacked.shape[0] // n]
    per = gathered.shape[0] // n
    chunks = [gathered[i * per:(i + 1) * per] for i in range(n)]
    if gather_list is not None:
        gather_list.extend(chunks)
        return gather_list
    return chunks


def alltoall_single_in(x, send_sizes, axis: str = "ep",
                       slot_rows: Optional[int] = None):
    """Ragged all-to-all, in-jit form (call under ``shard_map``).

    Parity: the variable-split ``alltoall_single`` / NCCL alltoallv
    (upstream python/paddle/distributed/communication/all_to_all.py).
    TPU-native: XLA collectives are static-shaped, so each destination's
    ragged segment is packed into a fixed slot of ``slot_rows`` rows and
    exchanged with ONE dense ``lax.all_to_all`` over the ICI ring
    (``lax.ragged_all_to_all`` would send only filled prefixes, but
    XLA:CPU has no kernel for it and CI runs on the CPU mesh).

    x: [n, ...] local rows sorted so rows destined for rank d form the
    d-th contiguous segment; ``send_sizes``: int32 [nranks] segment
    lengths (sum <= n, traced values allowed). Returns
    ``(recv, recv_sizes)`` where ``recv`` is [nranks, slot_rows, ...]
    (source-major; row s holds rank s's segment for this rank, zero
    padded) and ``recv_sizes`` is int32 [nranks].
    """
    n = x.shape[0]
    slot_rows = slot_rows or n
    send_sizes = send_sizes.astype(jnp.int32)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(send_sizes)[:-1]])
    slot = jnp.arange(slot_rows, dtype=jnp.int32)
    src_idx = offsets[:, None] + slot[None, :]
    valid = slot[None, :] < send_sizes[:, None]
    valid = valid.reshape(valid.shape + (1,) * (x.ndim - 1))
    send_buf = jnp.where(
        valid, x[jnp.clip(src_idx, 0, max(n - 1, 0))],
        jnp.zeros((), x.dtype))
    _record("alltoall_single", axis, send_buf)
    recv = jax.lax.all_to_all(send_buf, axis, 0, 0)
    recv_sizes = jax.lax.all_to_all(send_sizes, axis, 0, 0, tiled=True)
    return recv, recv_sizes


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, mesh=None):
    """All-to-all on dim 0 (paddle ``alltoall_single``), uniform or
    ragged splits.

    Uniform (no split sizes): ``in_tensor`` is the global array; rank
    r's chunk j goes to rank j; returns the transposed global array.

    Ragged: ``in_split_sizes`` is either one row of ``nranks`` ints
    (every rank sends the same split pattern) or an ``[nranks][nranks]``
    matrix whose row r is rank r's split list (the single-controller
    SPMD form of the reference's per-process argument). Each row must
    sum to the per-rank local length. Per-rank outputs generally have
    different lengths, so the ragged form returns a LIST of per-rank
    arrays (rank r's = the reference's ``out_tensor`` on process r);
    ``out_split_sizes`` is validated against the transpose if given.
    """
    if in_split_sizes is None and out_split_sizes is None:
        return alltoall(in_tensor, group=group, mesh=mesh)
    import numpy as np

    mesh = mesh or _active_mesh()
    axis = _group_axis(group)
    n = mesh.shape[axis]
    if in_split_sizes is None:
        # only out_split_sizes given: infer sends from the transpose
        outs = np.asarray(out_split_sizes, dtype=np.int32)
        if outs.ndim == 1:
            outs = np.tile(outs, (n, 1))
        in_split_sizes, out_split_sizes = outs.T, None
    splits = np.asarray(in_split_sizes, dtype=np.int32)
    if splits.ndim == 1:
        splits = np.tile(splits, (n, 1))
    if splits.shape != (n, n):
        raise ValueError(
            f"alltoall_single: in_split_sizes must be [{n}] or "
            f"[{n}][{n}], got shape {tuple(splits.shape)}")
    n_loc = in_tensor.shape[0] // n
    row_sums = splits.sum(axis=1)
    if not (row_sums == n_loc).all():
        raise ValueError(
            f"alltoall_single: each rank's in_split_sizes must sum to "
            f"its local length {n_loc}, got {row_sums.tolist()}")
    if out_split_sizes is not None:
        outs = np.asarray(out_split_sizes, dtype=np.int32)
        if outs.ndim == 1:
            outs = np.tile(outs, (n, 1))
        if not (outs == splits.T).all():
            raise ValueError(
                "alltoall_single: out_split_sizes must be the transpose "
                "of in_split_sizes")
    slot_rows = max(int(splits.max()), 1)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(axis), P(axis)),
        out_specs=(P(axis), P(axis)), check_vma=False,
    )
    def f(x_loc, sizes_loc):
        recv, recv_sizes = alltoall_single_in(
            x_loc, sizes_loc[0], axis=axis, slot_rows=slot_rows)
        return recv[None], recv_sizes[None]

    recv, recv_sizes = f(in_tensor, jnp.asarray(splits))
    recv = jax.device_get(recv)            # [n, n, slot_rows, ...]
    out = [
        jnp.concatenate(
            [recv[r, s, : int(splits[s, r])] for s in range(n)], axis=0)
        for r in range(n)
    ]
    if out_tensor is not None and isinstance(out_tensor, list):
        out_tensor.extend(out)
    return out


# ---------------------------------------------------------------------------
# p2p (parity: send/recv/isend/irecv, P2POp + batch_isend_irecv).
# Lockstep SPMD: a rank pair is one ppermute edge; every rank runs the
# same program, non-addressed ranks keep their input.
# ---------------------------------------------------------------------------
class _Task:
    def __init__(self, value=None):
        self.value = value

    def wait(self):
        if self.value is not None:
            jax.block_until_ready(self.value)
        return self.value


def _p2p(tensor, pairs, group=None, mesh=None):
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    def f(x):
        moved = jax.lax.ppermute(x, axis, pairs)
        dsts = jnp.asarray([d for _, d in pairs])
        i = jax.lax.axis_index(axis)
        hit = jnp.any(dsts == i)
        return jnp.where(hit, moved, x)

    return f(tensor)


def send(tensor, dst: int = 0, group=None, mesh=None):
    """Paired send: rank src's shard replaces rank dst's (the matching
    ``recv`` reads the returned array). Returns the post-exchange
    array."""
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)
    src = (dst - 1) % mesh.shape[axis]
    return _p2p(tensor, [(src, dst)], group, mesh)


def recv(tensor, src: int = 0, group=None, mesh=None):
    mesh = mesh or _active_mesh()
    axis = _group_axis(group)
    dst = (src + 1) % mesh.shape[axis]
    return _p2p(tensor, [(src, dst)], group, mesh)


def isend(tensor, dst: int = 0, group=None, mesh=None):
    return _Task(send(tensor, dst, group, mesh))


def irecv(tensor, src: int = 0, group=None, mesh=None):
    return _Task(recv(tensor, src, group, mesh))


def wait(tensor, group=None, use_calc_stream=True):
    jax.block_until_ready(tensor)
    return tensor


class P2POp:
    """Parity: paddle.distributed.P2POp — a deferred send/recv edge for
    batch_isend_irecv."""

    def __init__(self, op, tensor, peer, group=None):
        name = getattr(op, "__name__", str(op))
        if name not in ("send", "isend", "recv", "irecv"):
            raise ValueError(f"P2POp: unknown op {op}")
        self.is_send = "send" in name
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Execute every edge and return one task per op. Call-site parity
    for the reference's grouped-NCCL launcher: in lockstep SPMD each op
    is a canonical ring edge (see ``send``/``recv``); real pipelined
    transfer fusion lives in the compiled schedules
    (``distributed/pipeline.py``'s in-jit ppermute), not here."""
    tasks = []
    for o in p2p_op_list:
        val = (send(o.tensor, o.peer, o.group) if o.is_send
               else recv(o.tensor, o.peer, o.group))
        tasks.append(_Task(val))
    return tasks
