"""Flash attention: Pallas TPU kernel + XLA reference fallback.

Parity: paddle's flash_attn integration (phi kernels flash_attn_kernel.cu
wrapping libflashattn.so; python API paddle.nn.functional.flash_attention).

The Pallas kernel (implemented in this module for TPU backends) tiles
q/k/v into VMEM blocks, keeps the online-softmax running max/denominator
in registers, and never materializes the [sq, sk] score matrix in HBM.
The fallback is the straightforward XLA program — on short sequences XLA's
own fusion is already competitive.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _reference_attention(q, k, v, causal=False, scale=None, bias=None,
                         window=0):
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    if hq != hk:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else d ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if causal:
        sk = k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        if window:
            mask = jnp.logical_and(
                mask, jnp.triu(jnp.ones((sq, sk), bool),
                               k=sk - sq - window + 1))
        logits = jnp.where(mask, logits, jnp.float32(-1e30))
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _use_pallas(q) -> bool:
    import os

    b, s, h, d = q.shape
    # seq must tile into 128-blocks; head_dim only needs sublane (8)
    # alignment — the kernel zero-pads d to the lane width internally
    # (exact; see pallas_attention._fold), so 64/96-dim heads (GPT/ViT)
    # take the flash path instead of dense XLA attention.
    aligned = s % 128 == 0 and d % 8 == 0
    if os.environ.get("PADDLE_TPU_FORCE_PALLAS"):
        # CI/dryrun override: run the Pallas kernel in interpret mode off
        # TPU so the graft entry exercises the real kernel code path
        return aligned
    try:
        dev = q.devices() if hasattr(q, "devices") else set(jax.devices())
        platform = next(iter(dev)).platform if dev else jax.default_backend()
    except Exception:
        platform = jax.default_backend()
    if platform != "tpu":
        return False
    # Pallas kernel wants MXU/VPU-aligned tiles
    return aligned


def flash_attention(
    q,
    k,
    v,
    causal: bool = False,
    dropout_p: float = 0.0,
    training: bool = True,
    scale: Optional[float] = None,
    segment_ids=None,
    window_size: int = 0,
):
    """[batch, seq, heads, head_dim] attention. ``segment_ids`` gives the
    varlen/packed-sequence form (parity: flash_attn_varlen). Dropout
    applies only on the fallback path (flash+dropout is rare in practice;
    parity with paddle's flash_attn dropout is provided via the reference
    path)."""
    if window_size and not causal:
        # enforced up front so EVERY path (pallas, dense, segment,
        # dropout) rejects it identically instead of silently ignoring
        raise ValueError("window_size requires causal=True")
    if dropout_p > 0.0 and training:
        from ..nn import functional as F

        attn_mask = None
        if segment_ids is not None:
            if isinstance(segment_ids, (tuple, list)):
                seg_q, seg_kv = segment_ids
            else:
                seg_q = seg_kv = segment_ids
            attn_mask = (seg_q[:, None, :, None]
                         == seg_kv[:, None, None, :])
        if window_size:
            sq, sk = q.shape[1], k.shape[1]
            q_pos = jnp.arange(sq)[:, None] + (sk - sq)
            band = (q_pos - jnp.arange(sk)[None, :]) < window_size
            band = band[None, None]
            attn_mask = band if attn_mask is None else (attn_mask & band)
        return F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=dropout_p,
            is_causal=causal, scale=scale, training=training,
        )
    if _use_pallas(q):
        try:
            return _pallas_flash_attention(q, k, v, causal=causal,
                                           scale=scale,
                                           segment_ids=segment_ids,
                                           window=window_size)
        except Exception:
            pass
    if segment_ids is not None:
        return _segment_reference_attention(q, k, v, segment_ids,
                                            causal=causal, scale=scale,
                                            window=window_size)
    return _reference_attention(q, k, v, causal=causal, scale=scale,
                                window=window_size)


def _segment_reference_attention(q, k, v, segment_ids, causal=False,
                                 scale=None, window=0):
    if isinstance(segment_ids, (tuple, list)):
        seg_q, seg_kv = segment_ids
    else:
        seg_q = seg_kv = segment_ids
    bias_mask = seg_q[:, None, :, None] == seg_kv[:, None, None, :]
    bias = jnp.where(bias_mask, 0.0, jnp.float32(-1e30))
    return _reference_attention(q, k, v, causal=causal, scale=scale,
                                bias=bias, window=window)


# ---------------------------------------------------------------------------
# Pallas implementation
# ---------------------------------------------------------------------------
def _pallas_flash_attention(q, k, v, causal=False, scale=None,
                            segment_ids=None, window=0):
    from .. import flags
    from .pallas_attention import mha as pallas_mha

    # VMEM tile shape knobs (PT_FLAGS_flash_attention_block_{q,k});
    # mha clamps them to the actual (padded) sequence internally
    return pallas_mha(q, k, v, causal=causal, sm_scale=scale,
                      q_block=int(flags.flag("flash_attention_block_q")),
                      k_block=int(flags.flag("flash_attention_block_k")),
                      segment_ids=segment_ids, window=window)
