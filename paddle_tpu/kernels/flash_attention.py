"""Flash attention: Pallas TPU kernel + XLA reference fallback.

Parity: paddle's flash_attn integration (phi kernels flash_attn_kernel.cu
wrapping libflashattn.so; python API paddle.nn.functional.flash_attention).

The Pallas kernel (implemented in this module for TPU backends) tiles
q/k/v into VMEM blocks, keeps the online-softmax running max/denominator
in registers, and never materializes the [sq, sk] score matrix in HBM.
The fallback is the straightforward XLA program — on short sequences XLA's
own fusion is already competitive.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _reference_attention(q, k, v, causal=False, scale=None, bias=None):
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    if hq != hk:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else d ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if causal:
        sk = k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, jnp.float32(-1e30))
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _use_pallas(q) -> bool:
    try:
        dev = q.devices() if hasattr(q, "devices") else set(jax.devices())
        platform = next(iter(dev)).platform if dev else jax.default_backend()
    except Exception:
        platform = jax.default_backend()
    if platform != "tpu":
        return False
    b, s, h, d = q.shape
    # Pallas kernel wants MXU/VPU-aligned tiles
    return s % 128 == 0 and d % 128 == 0


def flash_attention(
    q,
    k,
    v,
    causal: bool = False,
    dropout_p: float = 0.0,
    training: bool = True,
    scale: Optional[float] = None,
):
    """[batch, seq, heads, head_dim] attention. Dropout applies only on the
    fallback path (flash+dropout is rare in practice; parity with paddle's
    flash_attn dropout is provided via the reference path)."""
    if dropout_p > 0.0 and training:
        from ..nn import functional as F

        return F.scaled_dot_product_attention(
            q, k, v, dropout_p=dropout_p, is_causal=causal, scale=scale,
            training=training,
        )
    if _use_pallas(q):
        try:
            return _pallas_flash_attention(q, k, v, causal=causal, scale=scale)
        except Exception:
            pass
    return _reference_attention(q, k, v, causal=causal, scale=scale)


# ---------------------------------------------------------------------------
# Pallas implementation
# ---------------------------------------------------------------------------
def _pallas_flash_attention(q, k, v, causal=False, scale=None):
    from .pallas_attention import mha as pallas_mha

    return pallas_mha(q, k, v, causal=causal, sm_scale=scale)
