"""Pallas TPU flash-attention kernel (forward + custom-VJP backward).

Parity: the reference's flash-attn integration (phi flash_attn kernels
wrapping libflashattn.so CUDA kernels, paddle/phi/kernels/gpu/
flash_attn_kernel.cu). This is the TPU-native equivalent: online-softmax
tiling in VMEM, fp32 running statistics, never materializing the
[sq, sk] score matrix in HBM.

Design notes (per /opt/skills/guides/pallas_guide.md):
  - grid = (batch*heads, q_blocks, k_blocks); k is the innermost
    (sequential) dimension so the running max/denominator live in VMEM
    scratch across k-steps.
  - blocks are MXU-aligned (q_block × head_dim and k_block × head_dim,
    head_dim 128-multiple); matmuls request fp32 accumulation via
    preferred_element_type.
  - causal masking skips fully-masked k-blocks via grid pruning in the
    index map (block_skip) — with the mask applied inside the diagonal
    blocks only.
  - backward recomputes probabilities blockwise (flash-attn v2 style),
    accumulating dq, dk, dv in fp32 VMEM scratch.

GQA is handled by folding the q-heads-per-kv-head factor into the batch
dimension outside the kernel.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_Q_BLOCK = 256
DEFAULT_K_BLOCK = 256
NEG_INF = -1e30


def _interpret() -> bool:
    # run the kernel in interpreter mode off-TPU (CPU CI parity tests)
    return jax.default_backend() != "tpu"


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scratch, l_scratch, acc_scratch,
                *, sm_scale, causal, q_block, k_block, k_seq_len):
    kb = pl.program_id(2)
    qb = pl.program_id(1)

    @pl.when(kb == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    q = q_ref[0]  # [q_block, d]
    k = k_ref[0]  # [k_block, d]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [q_block, k_block]
    s = s * sm_scale

    if causal:
        q_pos = qb * q_block + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, k_block), 0
        )
        k_pos = kb * k_block + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, k_block), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)

    m_prev = m_scratch[:]  # [q_block, 1]
    l_prev = l_scratch[:]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(s - m_new)  # [q_block, k_block] fp32
    alpha = jnp.exp(m_prev - m_new)  # [q_block, 1]
    l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)

    v = v_ref[0]  # [k_block, d]
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_scratch[:] = acc_scratch[:] * alpha + pv
    m_scratch[:] = m_new
    l_scratch[:] = l_new

    @pl.when(kb == pl.num_programs(2) - 1)
    def _finalize():
        l = l_scratch[:]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scratch[:] / l).astype(o_ref.dtype)


def _fwd_lse_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scratch, l_scratch,
                    acc_scratch, *, sm_scale, causal, q_block, k_block,
                    k_seq_len):
    """Same as _fwd_kernel but also writes logsumexp (for the backward).

    lse is stored lane-broadcast as [.., q_block, 128] — TPU block shapes
    need a 128-multiple minor dim (cf. jax's reference TPU flash attn).
    """
    _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scratch, l_scratch, acc_scratch,
                sm_scale=sm_scale, causal=causal, q_block=q_block,
                k_block=k_block, k_seq_len=k_seq_len)
    kb = pl.program_id(2)

    @pl.when(kb == pl.num_programs(2) - 1)
    def _():
        l = l_scratch[:]
        l = jnp.where(l == 0.0, 1.0, l)
        lse = m_scratch[:] + jnp.log(l)  # [q_block, 1]
        lse_ref[0] = jnp.broadcast_to(lse, (q_block, 128))


def _bwd_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, delta_ref,
                dq_ref, dk_ref, dv_ref, dk_scratch, dv_scratch,
                *, sm_scale, causal, q_block, k_block):
    """Grid: (bh, k_blocks, q_blocks) — q innermost so dk/dv accumulate in
    scratch; dq is accumulated into HBM via atomicity of one-q-block-per-
    (qb,kb) pass using input_output_alias (dq_ref starts zeroed)."""
    qb = pl.program_id(2)
    kb = pl.program_id(1)

    @pl.when(qb == 0)
    def _init():
        dk_scratch[:] = jnp.zeros_like(dk_scratch)
        dv_scratch[:] = jnp.zeros_like(dv_scratch)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, :1]  # lane-broadcast [q_block, 128] → [q_block, 1]
    delta = delta_ref[0][:, :1]

    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    if causal:
        q_pos = qb * q_block + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, k_block), 0
        )
        k_pos = kb * k_block + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, k_block), 1
        )
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jnp.exp(s - lse)  # [q_block, k_block]

    # dv += p^T do
    dv_scratch[:] += jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    # dp = do @ v^T
    dp = jax.lax.dot_general(
        do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - delta) * sm_scale  # [q_block, k_block]
    # dk += ds^T q
    dk_scratch[:] += jax.lax.dot_general(
        ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # dq partial for this (qb, kb): grid order is (bh, kb, qb) with qb
    # innermost, so dq cannot accumulate across kb in scratch — partials
    # land in distinct kb slices and are summed outside (_mha_bwd_impl)
    dqb = jax.lax.dot_general(
        ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dq_ref[0, 0] = dqb.astype(dq_ref.dtype)

    @pl.when(qb == pl.num_programs(2) - 1)
    def _fin():
        dk_ref[0] = dk_scratch[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scratch[:].astype(dv_ref.dtype)


def _pad_to(x, multiple, axis):
    size = x.shape[axis]
    rem = size % multiple
    if rem == 0:
        return x, size
    pad = multiple - rem
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths), size


def _mha_fwd_impl(q, k, v, sm_scale, causal, q_block, k_block,
                  return_lse=False):
    """q,k,v: [bh, s, d] (heads folded into batch)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    n_qb = pl.cdiv(sq, q_block)
    n_kb = pl.cdiv(sk, k_block)

    grid = (bh, n_qb, n_kb)
    q_spec = pl.BlockSpec((1, q_block, d), lambda b, i, j: (b, i, 0))
    k_spec = pl.BlockSpec((1, k_block, d), lambda b, i, j: (b, j, 0))
    v_spec = pl.BlockSpec((1, k_block, d), lambda b, i, j: (b, j, 0))
    o_spec = pl.BlockSpec((1, q_block, d), lambda b, i, j: (b, i, 0))
    scratch = [
        pltpu.VMEM((q_block, 1), jnp.float32),
        pltpu.VMEM((q_block, 1), jnp.float32),
        pltpu.VMEM((q_block, d), jnp.float32),
    ]
    cost = pl.CostEstimate(
        flops=4 * bh * sq * sk * d,
        bytes_accessed=2 * bh * (sq + sk) * d * 2,
        transcendentals=bh * sq * sk,
    )
    if not return_lse:
        kernel = functools.partial(
            _fwd_kernel, sm_scale=sm_scale, causal=causal,
            q_block=q_block, k_block=k_block, k_seq_len=sk,
        )
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[q_spec, k_spec, v_spec],
            out_specs=o_spec,
            out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            scratch_shapes=scratch,
            cost_estimate=cost,
            interpret=_interpret(),
        )(q, k, v)
    kernel = functools.partial(
        _fwd_lse_kernel, sm_scale=sm_scale, causal=causal,
        q_block=q_block, k_block=k_block, k_seq_len=sk,
    )
    lse_spec = pl.BlockSpec((1, q_block, 128), lambda b, i, j: (b, i, 0))
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[q_spec, k_spec, v_spec],
        out_specs=(o_spec, lse_spec),
        out_shape=(
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 128), jnp.float32),
        ),
        scratch_shapes=scratch,
        cost_estimate=cost,
        interpret=_interpret(),
    )(q, k, v)
    return o, lse[:, :, 0]


def _mha_bwd_impl(q, k, v, o, do, lse, sm_scale, causal, q_block, k_block):
    bh, sq, d = q.shape
    sk = k.shape[1]
    n_qb = pl.cdiv(sq, q_block)
    n_kb = pl.cdiv(sk, k_block)
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)
    # lane-broadcast the per-row vectors to a 128 minor dim (TPU tiling)
    lse = jnp.broadcast_to(lse[:, :, None], (bh, sq, 128))
    delta = jnp.broadcast_to(delta[:, :, None], (bh, sq, 128))

    grid = (bh, n_kb, n_qb)
    q_spec = pl.BlockSpec((1, q_block, d), lambda b, j, i: (b, i, 0))
    k_spec = pl.BlockSpec((1, k_block, d), lambda b, j, i: (b, j, 0))
    o_spec = q_spec
    lse_spec = pl.BlockSpec((1, q_block, 128), lambda b, j, i: (b, i, 0))
    # dq partials: one [q_block, d] slice per (kb) step → [bh, n_kb, sq, d]
    dq_spec = pl.BlockSpec((1, 1, q_block, d), lambda b, j, i: (b, j, i, 0))
    dk_spec = pl.BlockSpec((1, k_block, d), lambda b, j, i: (b, j, 0))

    kernel = functools.partial(
        _bwd_kernel, sm_scale=sm_scale, causal=causal,
        q_block=q_block, k_block=k_block,
    )
    dq_part, dk, dv = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[q_spec, k_spec, k_spec, o_spec, o_spec, lse_spec, lse_spec],
        out_specs=(dq_spec, dk_spec, dk_spec),
        out_shape=(
            jax.ShapeDtypeStruct((bh, n_kb, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, sk, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), q.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((k_block, d), jnp.float32),
            pltpu.VMEM((k_block, d), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=10 * bh * sq * sk * d,
            bytes_accessed=4 * bh * (sq + sk) * d * 2,
            transcendentals=bh * sq * sk,
        ),
        interpret=_interpret(),
    )(q, k, v, o, do, lse, delta)
    dq = jnp.sum(dq_part, axis=1).astype(q.dtype)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _mha_folded(q, k, v, sm_scale, causal, q_block, k_block):
    return _mha_fwd_impl(q, k, v, sm_scale, causal, q_block, k_block)


def _mha_folded_fwd(q, k, v, sm_scale, causal, q_block, k_block):
    o, lse = _mha_fwd_impl(q, k, v, sm_scale, causal, q_block, k_block,
                           return_lse=True)
    return o, (q, k, v, o, lse)


def _mha_folded_bwd(sm_scale, causal, q_block, k_block, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _mha_bwd_impl(q, k, v, o, do, lse, sm_scale, causal,
                               q_block, k_block)
    return dq, dk, dv


_mha_folded.defvjp(_mha_folded_fwd, _mha_folded_bwd)


def mha(q, k, v, causal: bool = False, sm_scale: Optional[float] = None,
        q_block: int = DEFAULT_Q_BLOCK, k_block: int = DEFAULT_K_BLOCK):
    """Flash attention. Layout [batch, seq, heads, head_dim]; supports GQA
    by repeating kv heads (grouped into the folded batch dim)."""
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    sm_scale = sm_scale if sm_scale is not None else d ** -0.5
    if hq != hk:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    # fold heads into batch: [b, s, h, d] -> [b*h, s, d]
    qf = q.transpose(0, 2, 1, 3).reshape(b * hq, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * hq, -1, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hq, -1, d)
    sk = kf.shape[1]
    qb = min(q_block, sq)
    kb = min(k_block, sk)
    of = _mha_folded(qf, kf, vf, sm_scale, causal, qb, kb)
    return of.reshape(b, hq, sq, d).transpose(0, 2, 1, 3)
