"""Pallas TPU flash-attention kernel (forward + custom-VJP backward).

Parity: the reference's flash-attn integration (phi flash_attn kernels
wrapping libflashattn.so CUDA kernels, paddle/phi/kernels/gpu/
flash_attn_kernel.cu, incl. the flash_attn_varlen entry point). This is
the TPU-native equivalent: online-softmax tiling in VMEM, fp32 running
statistics, never materializing the [sq, sk] score matrix in HBM.

Design notes (per /opt/skills/guides/pallas_guide.md):
  - forward grid = (batch*kv_heads, q_per_kv, q_blocks, k_blocks); k is
    the innermost (sequential) dimension so the running max/denominator
    live in VMEM scratch across k-steps.
  - GQA is native: q is viewed as [b*hk, rep, sq, d] and k/v as
    [b*hk, sk, d]; the kv block index map ignores the rep dimension, so
    kv is NEVER materialized rep times in HBM (no jnp.repeat).
  - causal masking prunes fully-masked k-blocks: the kv index map clamps
    the block index at the diagonal (a revisited block issues no DMA) and
    the kernel body is skipped under pl.when, so causal runs ~half the
    FLOPs and ~half the kv HBM traffic. The mask itself is applied only
    in diagonal-straddling blocks.
  - backward is two passes (flash-v2 style): a dq kernel with k innermost
    accumulating dq in VMEM scratch, and a dk/dv kernel with (rep, q)
    innermost accumulating dk/dv in VMEM scratch — no [bh, n_kb, sq, d]
    HBM partials anywhere; every gradient's HBM footprint equals its
    final size. The dk/dv pass also performs the GQA head-group reduction
    in-register (sum over rep lands in the same scratch accumulator).
  - varlen/packed sequences via segment ids (parity with
    flash_attn_varlen): tokens attend only within equal segment id;
    padding can be given a sentinel segment.
  - blocks are MXU-aligned; all matmuls request fp32 accumulation via
    preferred_element_type; per-row stats are carried lane-broadcast
    ([q_block, 128]) to keep Mosaic layouts trivial.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# v5e-swept defaults (876M bench shape, b4 x s2048 x h24 x d128, causal):
# 256/256 ran fwd 5.36ms / fwd+bwd 13.9ms; 512/1024 2.16/6.49;
# 1024/1024 1.97/6.20 — 2.2x over 256-blocks (grid-step overhead
# dominates small tiles; each 256x256 tile is ~0.2us of MXU work) and
# ahead of the jax-bundled TPU flash kernel's 1.31/6.95 on fwd+bwd.
# 2048-size blocks fail to compile (VMEM). Shorter sequences clamp in
# _fold, so the large default is safe for every caller.
DEFAULT_Q_BLOCK = 1024
DEFAULT_K_BLOCK = 1024
NEG_INF = -1e30
LANES = 128


def _interpret() -> bool:
    # run the kernel in interpreter mode off-TPU (CPU CI parity tests)
    return jax.default_backend() != "tpu"


def _params(*parallel_then_arbitrary: str):
    from ..jax_compat import tpu_compiler_params

    return tpu_compiler_params(dimension_semantics=parallel_then_arbitrary)


def _causal_j_max(i: int, q_block: int, k_block: int):
    """Last kv block index with any unmasked element for q block i."""
    return ((i + 1) * q_block - 1) // k_block


def _causal_i_min(j: int, q_block: int, k_block: int):
    """First q block index with any unmasked element for kv block j."""
    return (j * k_block) // q_block


def _window_j_min(i: int, q_block: int, k_block: int, window: int):
    """First kv block with any in-window element for q block i
    (sliding window: only keys with q_pos − k_pos < window count; the
    earliest relevant k_pos for this q block is i·q_block − window + 1).
    """
    lo = i * q_block - window + 1
    return jnp.maximum(lo, 0) // k_block


def _window_i_max(j: int, q_block: int, k_block: int, window: int):
    """Last q block with any in-window element for kv block j (largest
    relevant q_pos is (j+1)·k_block − 1 + window − 1)."""
    return ((j + 1) * k_block - 1 + window - 1) // q_block


def _block_mask(s, qb_idx, kb_idx, q_block, k_block, causal, q_seg, k_seg,
                window=0):
    """Apply causal/sliding-window/segment masking to a
    [q_block, k_block] score tile.

    Only called where it can matter: causal masking only on
    diagonal-straddling blocks (callers prune/skip fully-masked blocks).
    ``window`` > 0 (Mistral-style local attention, parity: flash_attn
    window_size) additionally masks keys more than window−1 positions
    behind the query.
    """
    mask = None
    if causal or window:
        q_pos = qb_idx * q_block + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, k_block), 0
        )
        k_pos = kb_idx * k_block + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, k_block), 1
        )
        mask = q_pos >= k_pos
        if window:
            mask = jnp.logical_and(mask, q_pos - k_pos < window)
    if q_seg is not None:
        seg = q_seg == k_seg  # [q_block, 1] == [1, k_block] -> broadcast
        mask = seg if mask is None else jnp.logical_and(mask, seg)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    return s


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(*refs, sm_scale, causal, q_block, k_block, n_kb,
                with_lse, with_segments, window):
    if with_segments:
        q_ref, k_ref, v_ref, qseg_ref, kseg_ref, *out_refs = refs
    else:
        q_ref, k_ref, v_ref, *out_refs = refs
        qseg_ref = kseg_ref = None
    if with_lse:
        o_ref, lse_ref, m_scratch, l_scratch, acc_scratch = out_refs
    else:
        o_ref, m_scratch, l_scratch, acc_scratch = out_refs
        lse_ref = None

    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    def _step():
        q = q_ref[0, 0]  # [q_block, d]
        k = k_ref[0]  # [k_block, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        q_seg = qseg_ref[0][:, :1] if qseg_ref is not None else None
        k_seg = kseg_ref[...][:1, :] if kseg_ref is not None else None
        if causal or window or q_seg is not None:
            s = _block_mask(s, i, j, q_block, k_block, causal, q_seg,
                            k_seg, window)

        m_prev = m_scratch[:, :1]  # [q_block, 1]
        l_prev = l_scratch[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # [q_block, k_block] fp32
        alpha = jnp.exp(m_prev - m_new)  # [q_block, 1]
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)

        v = v_ref[0]  # [k_block, d]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scratch[:] = acc_scratch[:] * alpha + pv
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    # pruned iterations (causal: fully above the diagonal; window:
    # fully behind the window) do no work; the kv index map clamps their
    # block index so they issue no DMA either.
    if causal and window:
        pl.when(jnp.logical_and(
            j <= _causal_j_max(i, q_block, k_block),
            j >= _window_j_min(i, q_block, k_block, window)))(_step)
    elif causal:
        pl.when(j <= _causal_j_max(i, q_block, k_block))(_step)
    else:
        _step()

    @pl.when(j == n_kb - 1)
    def _finalize():
        l = l_scratch[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scratch[:] / l).astype(o_ref.dtype)
        if lse_ref is not None:
            lse = m_scratch[:, :1] + jnp.log(l)  # [q_block, 1]
            lse_ref[0, 0] = jnp.broadcast_to(lse, (q_block, LANES))


def _mha_fwd_impl(q, k, v, qseg, kseg, sm_scale, causal, q_block, k_block,
                  return_lse=False, window=0):
    """q: [g, rep, sq, d]; k, v: [g, sk, d]; g = batch * kv_heads.

    qseg: [g, sq, LANES] int32 or None; kseg: [g, sk] int32 or None.
    """
    g, rep, sq, d = q.shape
    sk = k.shape[1]
    n_qb = sq // q_block
    n_kb = sk // k_block

    grid = (g, rep, n_qb, n_kb)

    def kv_index(b, r, i, j):
        if causal:
            j = jnp.minimum(j, _causal_j_max(i, q_block, k_block))
        if window:
            j = jnp.maximum(j, _window_j_min(i, q_block, k_block, window))
        return (b, j, 0)

    q_spec = pl.BlockSpec((1, 1, q_block, d), lambda b, r, i, j: (b, r, i, 0))
    k_spec = pl.BlockSpec((1, k_block, d), kv_index)
    o_spec = q_spec
    in_specs = [q_spec, k_spec, k_spec]
    inputs = [q, k, v]
    if qseg is not None:
        in_specs.append(pl.BlockSpec((1, q_block, LANES),
                                     lambda b, r, i, j: (b, i, 0)))
        in_specs.append(pl.BlockSpec(
            (1, k_block),
            (lambda b, r, i, j: (b, kv_index(b, r, i, j)[1]))))
        inputs += [qseg, kseg]
    scratch = [
        pltpu.VMEM((q_block, LANES), jnp.float32),
        pltpu.VMEM((q_block, LANES), jnp.float32),
        pltpu.VMEM((q_block, d), jnp.float32),
    ]
    flops = 4 * g * rep * sq * sk * d // (2 if causal else 1)
    cost = pl.CostEstimate(
        flops=flops,
        bytes_accessed=(q.size + 2 * g * sk * d + q.size) * 2,
        transcendentals=g * rep * sq * sk // (2 if causal else 1),
    )
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, q_block=q_block,
        k_block=k_block, n_kb=n_kb, with_lse=return_lse,
        with_segments=qseg is not None, window=window,
    )
    params = _params("parallel", "parallel", "parallel", "arbitrary")
    if not return_lse:
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=o_spec,
            out_shape=jax.ShapeDtypeStruct((g, rep, sq, d), q.dtype),
            scratch_shapes=scratch,
            cost_estimate=cost,
            compiler_params=params,
            interpret=_interpret(),
        )(*inputs)
    lse_spec = pl.BlockSpec((1, 1, q_block, LANES),
                            lambda b, r, i, j: (b, r, i, 0))
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=(o_spec, lse_spec),
        out_shape=(
            jax.ShapeDtypeStruct((g, rep, sq, d), q.dtype),
            jax.ShapeDtypeStruct((g, rep, sq, LANES), jnp.float32),
        ),
        scratch_shapes=scratch,
        cost_estimate=cost,
        compiler_params=params,
        interpret=_interpret(),
    )(*inputs)
    return o, lse[:, :, :, 0]


# ---------------------------------------------------------------------------
# backward: dq pass (grid k-innermost, dq accumulates in VMEM scratch)
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(*refs, sm_scale, causal, q_block, k_block, n_kb,
                   with_segments, window):
    if with_segments:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref,
         kseg_ref, dq_ref, dq_scratch) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
         dq_scratch) = refs
        qseg_ref = kseg_ref = None

    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        dq_scratch[:] = jnp.zeros_like(dq_scratch)

    def _step():
        q = q_ref[0, 0]
        k = k_ref[0]
        v = v_ref[0]
        # matmul operands stay in the INPUT dtype (bf16 in training) with
        # f32 accumulation — flash-v2 precision. f32 operands would run
        # the MXU at half rate on v5e/v5p.
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        q_seg = qseg_ref[0][:, :1] if qseg_ref is not None else None
        k_seg = kseg_ref[...][:1, :] if kseg_ref is not None else None

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal or window or q_seg is not None:
            s = _block_mask(s, i, j, q_block, k_block, causal, q_seg,
                            k_seg, window)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dq_scratch[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal and window:
        pl.when(jnp.logical_and(
            j <= _causal_j_max(i, q_block, k_block),
            j >= _window_j_min(i, q_block, k_block, window)))(_step)
    elif causal:
        pl.when(j <= _causal_j_max(i, q_block, k_block))(_step)
    else:
        _step()

    @pl.when(j == n_kb - 1)
    def _fin():
        dq_ref[0, 0] = dq_scratch[:].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# backward: dk/dv pass (grid (rep, q)-innermost, dk/dv accumulate in VMEM;
# the GQA group-sum over rep happens in the same accumulator)
# ---------------------------------------------------------------------------
def _bwd_dkv_kernel(*refs, sm_scale, causal, q_block, k_block, n_qb, rep,
                    with_segments, window):
    if with_segments:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref,
         kseg_ref, dk_ref, dv_ref, dk_scratch, dv_scratch) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
         dk_scratch, dv_scratch) = refs
        qseg_ref = kseg_ref = None

    j = pl.program_id(1)
    r = pl.program_id(2)
    i = pl.program_id(3)

    @pl.when(jnp.logical_and(r == 0, i == 0))
    def _init():
        dk_scratch[:] = jnp.zeros_like(dk_scratch)
        dv_scratch[:] = jnp.zeros_like(dv_scratch)

    def _step():
        q = q_ref[0, 0]
        k = k_ref[0]
        v = v_ref[0]
        # input-dtype matmul operands, f32 accumulation (see dq kernel)
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        q_seg = qseg_ref[0][:, :1] if qseg_ref is not None else None
        k_seg = kseg_ref[...][:1, :] if kseg_ref is not None else None

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal or window or q_seg is not None:
            s = _block_mask(s, i, j, q_block, k_block, causal, q_seg,
                            k_seg, window)
        p = jnp.exp(s - lse)  # [q_block, k_block] f32
        # dv += p^T do
        dv_scratch[:] += jax.lax.dot_general(
            p.astype(q.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        # dk += ds^T q
        dk_scratch[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal and window:
        pl.when(jnp.logical_and(
            i >= _causal_i_min(j, q_block, k_block),
            i <= _window_i_max(j, q_block, k_block, window)))(_step)
    elif causal:
        pl.when(i >= _causal_i_min(j, q_block, k_block))(_step)
    else:
        _step()

    @pl.when(jnp.logical_and(r == rep - 1, i == n_qb - 1))
    def _fin():
        dk_ref[0] = dk_scratch[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scratch[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# backward: FUSED single pass (flash-v2 backward proper).
#
# The two-pass layout above runs 7 tile-matmuls (s and dp are computed
# twice) and the full exp/mask/ds VPU chain twice — and the round-4
# profile showed the backward VPU-bound at ~31% of roofline. This kernel
# computes s/p/dp/ds ONCE per (j, i) tile and emits all three gradients:
# dk/dv accumulate in VMEM scratch exactly as before (j is the outer
# grid dim), while dq — whose natural accumulation order is transposed —
# is written as per-j f32 PARTIALS [g, n_kb, rep, sq, d] that one XLA
# reduction folds afterwards. 5 tile-matmuls, one VPU chain; extra HBM
# is n_kb x sizeof(dq) for the partials, so the fused path is gated to
# small n_kb (large k_block keeps n_kb = seq/1024) and falls back to the
# two-pass kernels beyond it. Races: every partial block is written by
# exactly one grid step; fully-masked steps zero-fill theirs.
# ---------------------------------------------------------------------------
_FUSED_BWD_MAX_KB = 4


def _bwd_fused_kernel(*refs, sm_scale, causal, q_block, k_block, n_qb, rep,
                      with_segments, window):
    if with_segments:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref,
         kseg_ref, dqp_ref, dk_ref, dv_ref, dk_scratch, dv_scratch) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dqp_ref,
         dk_ref, dv_ref, dk_scratch, dv_scratch) = refs
        qseg_ref = kseg_ref = None

    j = pl.program_id(1)
    r = pl.program_id(2)
    i = pl.program_id(3)

    @pl.when(jnp.logical_and(r == 0, i == 0))
    def _init():
        dk_scratch[:] = jnp.zeros_like(dk_scratch)
        dv_scratch[:] = jnp.zeros_like(dv_scratch)

    def _step():
        q = q_ref[0, 0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        q_seg = qseg_ref[0][:, :1] if qseg_ref is not None else None
        k_seg = kseg_ref[...][:1, :] if kseg_ref is not None else None

        # input-dtype matmul operands, f32 accumulation (flash-v2)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        if causal or window or q_seg is not None:
            s = _block_mask(s, i, j, q_block, k_block, causal, q_seg,
                            k_seg, window)
        p = jnp.exp(s - lse)  # computed ONCE for all three grads
        dv_scratch[:] += jax.lax.dot_general(
            p.astype(q.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk_scratch[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dqp_ref[0, 0, 0] = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dqp_ref.dtype)

    def _skip():
        # fully-masked tile: its dq partial block must still be defined
        dqp_ref[0, 0, 0] = jnp.zeros_like(dqp_ref[0, 0, 0])

    if causal and window:
        live = jnp.logical_and(
            i >= _causal_i_min(j, q_block, k_block),
            i <= _window_i_max(j, q_block, k_block, window))
        pl.when(live)(_step)
        pl.when(jnp.logical_not(live))(_skip)
    elif causal:
        live = i >= _causal_i_min(j, q_block, k_block)
        pl.when(live)(_step)
        pl.when(jnp.logical_not(live))(_skip)
    else:
        _step()

    @pl.when(jnp.logical_and(r == rep - 1, i == n_qb - 1))
    def _fin():
        dk_ref[0] = dk_scratch[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scratch[:].astype(dv_ref.dtype)


def _mha_bwd_impl(q, k, v, o, do, lse, qseg, kseg, sm_scale, causal,
                  q_block, k_block, dlse=None, window=0):
    g, rep, sq, d = q.shape
    sk = k.shape[1]
    n_qb = sq // q_block
    n_kb = sk // k_block
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)
    if dlse is not None:
        # lse cotangent folds into delta: ds = p*(dp - (delta - dlse))
        delta = delta - dlse.astype(jnp.float32)
    # lane-broadcast the per-row vectors to a 128 minor dim (TPU tiling)
    lse_b = jnp.broadcast_to(lse[..., None], (g, rep, sq, LANES))
    delta_b = jnp.broadcast_to(delta[..., None], (g, rep, sq, LANES))

    q_spec = pl.BlockSpec((1, 1, q_block, d), lambda b, r, i, j: (b, r, i, 0))
    row_spec = pl.BlockSpec((1, 1, q_block, LANES),
                            lambda b, r, i, j: (b, r, i, 0))

    def kv_index(b, r, i, j):
        if causal:
            j = jnp.minimum(j, _causal_j_max(i, q_block, k_block))
        if window:
            j = jnp.maximum(j, _window_j_min(i, q_block, k_block, window))
        return (b, j, 0)

    k_spec = pl.BlockSpec((1, k_block, d), kv_index)
    in_specs = [q_spec, k_spec, k_spec, q_spec, row_spec, row_spec]
    inputs = [q, k, v, do, lse_b, delta_b]
    if qseg is not None:
        in_specs.append(pl.BlockSpec((1, q_block, LANES),
                                     lambda b, r, i, j: (b, i, 0)))
        in_specs.append(pl.BlockSpec(
            (1, k_block), lambda b, r, i, j: (b, kv_index(b, r, i, j)[1])))
        inputs += [qseg, kseg]

    fused = n_kb <= _FUSED_BWD_MAX_KB
    if not fused:
        dq = pl.pallas_call(
            functools.partial(
                _bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                q_block=q_block, k_block=k_block, n_kb=n_kb,
                with_segments=qseg is not None, window=window,
            ),
            grid=(g, rep, n_qb, n_kb),
            in_specs=in_specs,
            out_specs=q_spec,
            out_shape=jax.ShapeDtypeStruct((g, rep, sq, d), q.dtype),
            scratch_shapes=[pltpu.VMEM((q_block, d), jnp.float32)],
            cost_estimate=pl.CostEstimate(
                flops=6 * g * rep * sq * sk * d // (2 if causal else 1),
                bytes_accessed=4 * g * rep * sq * d * 2 + 2 * g * sk * d * 2,
                transcendentals=g * rep * sq * sk // (2 if causal else 1),
            ),
            compiler_params=_params("parallel", "parallel", "parallel",
                                    "arbitrary"),
            interpret=_interpret(),
        )(*inputs)

    # dk/dv pass (fused: + dq partials): grid reordered (g, kb, rep, qb)
    def q_index2(b, j, r, i):
        if causal:
            i = jnp.maximum(i, _causal_i_min(j, q_block, k_block))
        if window:
            i = jnp.minimum(i, _window_i_max(j, q_block, k_block, window))
        return (b, r, i, 0)

    q_spec2 = pl.BlockSpec((1, 1, q_block, d), q_index2)
    row_spec2 = pl.BlockSpec(
        (1, 1, q_block, LANES),
        lambda b, j, r, i: q_index2(b, j, r, i))
    kv_spec2 = pl.BlockSpec((1, k_block, d), lambda b, j, r, i: (b, j, 0))
    in_specs2 = [q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2]
    if qseg is not None:
        in_specs2.append(pl.BlockSpec(
            (1, q_block, LANES),
            lambda b, j, r, i: (b, q_index2(b, j, r, i)[2], 0)))
        in_specs2.append(pl.BlockSpec((1, k_block),
                                      lambda b, j, r, i: (b, j)))

    if fused:
        dqp_spec = pl.BlockSpec(
            (1, 1, 1, q_block, d), lambda b, j, r, i: (b, j, r, i, 0))
        dq_part, dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_fused_kernel, sm_scale=sm_scale, causal=causal,
                q_block=q_block, k_block=k_block, n_qb=n_qb, rep=rep,
                with_segments=qseg is not None, window=window,
            ),
            grid=(g, n_kb, rep, n_qb),
            in_specs=in_specs2,
            out_specs=(dqp_spec, kv_spec2, kv_spec2),
            out_shape=(
                jax.ShapeDtypeStruct((g, n_kb, rep, sq, d), jnp.float32),
                jax.ShapeDtypeStruct((g, sk, d), q.dtype),
                jax.ShapeDtypeStruct((g, sk, d), q.dtype),
            ),
            scratch_shapes=[
                pltpu.VMEM((k_block, d), jnp.float32),
                pltpu.VMEM((k_block, d), jnp.float32),
            ],
            cost_estimate=pl.CostEstimate(
                flops=10 * g * rep * sq * sk * d // (2 if causal else 1),
                bytes_accessed=(4 * g * rep * sq * d * 2
                                + 2 * g * sk * d * 2
                                + 4 * g * n_kb * rep * sq * d),
                transcendentals=g * rep * sq * sk
                // (2 if causal else 1),
            ),
            compiler_params=_params("parallel", "parallel", "arbitrary",
                                    "arbitrary"),
            interpret=_interpret(),
        )(*inputs)
        dq = dq_part.sum(axis=1).astype(q.dtype)
        return dq, dk, dv

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
            q_block=q_block, k_block=k_block, n_qb=n_qb, rep=rep,
            with_segments=qseg is not None, window=window,
        ),
        grid=(g, n_kb, rep, n_qb),
        in_specs=in_specs2,
        out_specs=(kv_spec2, kv_spec2),
        out_shape=(
            jax.ShapeDtypeStruct((g, sk, d), q.dtype),
            jax.ShapeDtypeStruct((g, sk, d), q.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((k_block, d), jnp.float32),
            pltpu.VMEM((k_block, d), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=8 * g * rep * sq * sk * d // (2 if causal else 1),
            bytes_accessed=4 * g * rep * sq * d * 2 + 2 * g * sk * d * 2,
            transcendentals=g * rep * sq * sk // (2 if causal else 1),
        ),
        compiler_params=_params("parallel", "parallel", "arbitrary",
                                "arbitrary"),
        interpret=_interpret(),
    )(*inputs)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom VJP over the folded [g, rep, s, d] layout
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _mha_folded(q, k, v, qseg, kseg, sm_scale, causal, q_block, k_block,
                window):
    return _mha_fwd_impl(q, k, v, qseg, kseg, sm_scale, causal, q_block,
                         k_block, window=window)


def _mha_folded_fwd(q, k, v, qseg, kseg, sm_scale, causal, q_block, k_block,
                    window):
    o, lse = _mha_fwd_impl(q, k, v, qseg, kseg, sm_scale, causal, q_block,
                           k_block, return_lse=True, window=window)
    return o, (q, k, v, o, lse, qseg, kseg)


def _mha_folded_bwd(sm_scale, causal, q_block, k_block, window, res, do):
    q, k, v, o, lse, qseg, kseg = res
    dq, dk, dv = _mha_bwd_impl(q, k, v, o, do, lse, qseg, kseg, sm_scale,
                               causal, q_block, k_block, window=window)
    return dq, dk, dv, None, None


_mha_folded.defvjp(_mha_folded_fwd, _mha_folded_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _mha_lse_folded(q, k, v, qseg, kseg, sm_scale, causal, q_block, k_block,
                    window):
    """Like _mha_folded but also returns logsumexp — the merge statistic
    ring/context-parallel attention needs to combine per-block results."""
    return _mha_fwd_impl(q, k, v, qseg, kseg, sm_scale, causal, q_block,
                         k_block, return_lse=True, window=window)


def _mha_lse_folded_fwd(q, k, v, qseg, kseg, sm_scale, causal, q_block,
                        k_block, window):
    o, lse = _mha_fwd_impl(q, k, v, qseg, kseg, sm_scale, causal, q_block,
                           k_block, return_lse=True, window=window)
    return (o, lse), (q, k, v, o, lse, qseg, kseg)


def _mha_lse_folded_bwd(sm_scale, causal, q_block, k_block, window, res,
                        cts):
    q, k, v, o, lse, qseg, kseg = res
    do, dlse = cts
    dq, dk, dv = _mha_bwd_impl(q, k, v, o, do, lse, qseg, kseg, sm_scale,
                               causal, q_block, k_block, dlse=dlse,
                               window=window)
    return dq, dk, dv, None, None


_mha_lse_folded.defvjp(_mha_lse_folded_fwd, _mha_lse_folded_bwd)


SegmentIds = Tuple[jax.Array, jax.Array]


def _fold(q, k, v, segment_ids, q_block, k_block):
    b, sq, hq, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if hq % hk:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hk}")
    rep = hq // hk
    # Unaligned head_dim (64/96 in GPT/ViT configs): zero-pad to the lane
    # width. Exact — padded dims contribute 0 to q·k scores and 0 to the
    # padded output columns, which the caller slices off. sm_scale is
    # computed from the TRUE d by the caller before padding. Cheaper than
    # falling back to dense XLA attention, which materializes [sq, sk].
    if d % LANES:
        d_pad = ((d + LANES - 1) // LANES) * LANES
        pad = [(0, 0)] * 3 + [(0, d_pad - d)]
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
        d = d_pad
    # choose blocks that tile the sequence exactly: prefer the requested
    # block, else halve until one divides (any 128-multiple seq len
    # divides at 128)
    def _fit(blk, sl):
        blk = min(blk, sl)
        while blk > 128 and sl % blk:
            blk //= 2
        if sl % blk:
            # requested block shares no power-of-two divisor with the
            # seq (e.g. 768 vs 2048) — fall back to the universal 128
            blk = 128
        return blk

    qb = _fit(q_block, sq)
    kb = _fit(k_block, sk)
    if sq % qb or sk % kb:
        raise ValueError(
            f"seq lens ({sq}, {sk}) must be multiples of 128")

    # [b, s, h, d] -> q: [b*hk, rep, sq, d]; kv: [b*hk, sk, d]
    qf = q.transpose(0, 2, 1, 3).reshape(b, hk, rep, sq, d)
    qf = qf.reshape(b * hk, rep, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * hk, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hk, sk, d)

    qseg = kseg = None
    if segment_ids is not None:
        if isinstance(segment_ids, (tuple, list)):
            q_ids, kv_ids = segment_ids
        else:
            q_ids = kv_ids = segment_ids
        q_ids = jnp.asarray(q_ids, jnp.int32)
        kv_ids = jnp.asarray(kv_ids, jnp.int32)
        # replicate per kv-head group: [b, s] -> [b*hk, ...]
        qseg = jnp.broadcast_to(q_ids[:, None, :, None],
                                (b, hk, sq, LANES)).reshape(b * hk, sq, LANES)
        kseg = jnp.broadcast_to(kv_ids[:, None, :],
                                (b, hk, sk)).reshape(b * hk, sk)
    return qf, kf, vf, qseg, kseg, qb, kb


def mha(q, k, v, causal: bool = False, sm_scale: Optional[float] = None,
        q_block: int = DEFAULT_Q_BLOCK, k_block: int = DEFAULT_K_BLOCK,
        segment_ids: Optional[Union[jax.Array, SegmentIds]] = None,
        window: int = 0):
    """Flash attention over [batch, seq, heads, head_dim].

    GQA (kv_heads < q_heads) is handled inside the kernel's index maps —
    kv is never replicated in HBM. ``segment_ids`` enables varlen/packed
    attention (parity: flash_attn_varlen): either one [b, s] int array
    (self-attention) or a (q_ids [b, sq], kv_ids [b, sk]) pair; tokens
    attend only where ids match.
    """
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    sm_scale = sm_scale if sm_scale is not None else d ** -0.5
    if window and not causal:
        raise ValueError("sliding window requires causal=True")
    qf, kf, vf, qseg, kseg, qb, kb = _fold(q, k, v, segment_ids,
                                           q_block, k_block)
    of = _mha_folded(qf, kf, vf, qseg, kseg, sm_scale, causal, qb, kb,
                     window)
    of = of.reshape(b, hq, sq, of.shape[-1]).transpose(0, 2, 1, 3)
    return of[..., :d]  # drop lane padding for unaligned head_dim


def mha_with_lse(q, k, v, causal: bool = False,
                 sm_scale: Optional[float] = None,
                 q_block: int = DEFAULT_Q_BLOCK,
                 k_block: int = DEFAULT_K_BLOCK,
                 segment_ids: Optional[Union[jax.Array, SegmentIds]] = None,
                 window: int = 0):
    """Flash attention that also returns logsumexp [b, heads, sq] — the
    statistic ring/context-parallel callers need to merge per-block
    partial results (fully differentiable, incl. the lse output)."""
    b, sq, hq, d = q.shape
    sm_scale = sm_scale if sm_scale is not None else d ** -0.5
    if window and not causal:
        raise ValueError("sliding window requires causal=True")
    qf, kf, vf, qseg, kseg, qb, kb = _fold(q, k, v, segment_ids,
                                           q_block, k_block)
    of, lse = _mha_lse_folded(qf, kf, vf, qseg, kseg, sm_scale, causal,
                              qb, kb, window)
    o = of.reshape(b, hq, sq, of.shape[-1]).transpose(0, 2, 1, 3)
    return o[..., :d], lse.reshape(b, hq, sq)
