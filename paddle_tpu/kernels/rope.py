"""Rotary position embedding.

Parity: phi fused_rope kernel (paddle/phi/kernels/fusion/gpu/
fused_rope_kernel.cu). On TPU this is a bandwidth-bound elementwise op
that XLA fuses into the surrounding attention prologue; the jnp form below
compiles to exactly that fusion, so no Pallas kernel is needed (verified
by profile — it never appears as a standalone HBM pass).

Uses the half-rotation (Neox/Llama) convention: rotate_half.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp


def rope_frequencies(
    head_dim: int,
    max_seq_len: int,
    theta: float = 10000.0,
    dtype=jnp.float32,
    scaling_factor: float = 1.0,
):
    """Precompute cos/sin tables [max_seq_len, head_dim//2]."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    t = jnp.arange(max_seq_len, dtype=jnp.float32) / scaling_factor
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(
    q: jnp.ndarray,
    k: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    position_ids: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """q,k: [batch, seq, heads, head_dim]; cos/sin: [max_seq, head_dim//2].

    position_ids: optional [batch, seq] gather indices (decode caches).
    """
    seq = q.shape[1]
    if position_ids is None:
        c = cos[:seq][None, :, None, :]  # [1, s, 1, d/2]
        s = sin[:seq][None, :, None, :]
    else:
        c = cos[position_ids][:, :, None, :]
        s = sin[position_ids][:, :, None, :]

    def rot(x):
        xf = x.astype(jnp.float32)
        x1, x2 = jnp.split(xf, 2, axis=-1)
        out = jnp.concatenate(
            [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1
        )
        return out.astype(x.dtype)

    return rot(q), rot(k)
