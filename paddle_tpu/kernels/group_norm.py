"""Fused Pallas GroupNorm(+SiLU) for channels-last (NHWC) activations.

Parity: the reference's fused group_norm kernels
(paddle/phi/kernels/fusion/gpu/fused_groupnorm — GroupNormNHWC
forward/backward used by the SD-UNet path in ppdiffusers).

Why a kernel when XLA can fuse elementwise chains: GroupNorm is a
CASCADED reduction — per-(sample, group) moments over (H·W·C/G)
elements, then a normalize+affine(+SiLU) elementwise pass over the same
tensor. XLA compiles this as separate reduce and map fusions with the
activation streamed from HBM once per pass (2-3 reads + 1 write), and
under NCHW it additionally brackets the chain with relayout copies (the
round-5 SD-UNet capture: 40% of device time in {1,0,3,2}<->{0,1,3,2}
copies, 9.0% MFU). This kernel reads the activation from HBM ONCE,
keeps the (sample, group-block) tile VMEM-resident, computes moments +
normalize + affine + optional SiLU in one grid step, and writes once —
the RedFuser-style cascaded-reduction fusion, with the group-channel
reductions expressed as tiny one-hot matmuls so no lane-crossing
reshape is needed.

Moments use the numerically-stable two-pass form (mean first, then
centered second moment) — both passes run over the VMEM-resident tile,
so HBM sees a single pass; a streaming Welford merge is unnecessary at
these tile sizes and would cost extra VPU work.

Backward is a second fused kernel over the same tiling: recomputes
x̂ from saved per-group (mean, rstd), applies the SiLU cotangent chain
when the activation was fused, and emits dx in one read of (x, dy) +
one write, with per-(sample, block) dγ/dβ partials reduced outside (an
[n, c] array — negligible next to the activations).

Grid: ``(n, c // c_block)`` where ``c_block`` is a group-aligned
channel slab chosen to fit the VMEM budget; every group lies wholly
inside one slab, so each grid step owns its statistics. Tensors whose
per-sample slab exceeds the budget fall back to the lax reference
(``supports_fused`` returns False) — same numerics, still
transpose-free under the NHWC layout policy.

Interpreter mode (non-TPU backends) runs the same kernels via
``interpret=True``; ``group_norm_reference`` is the numeric source of
truth the tests compare against.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# VMEM the fused path may assume per grid step: the backward holds
# x, dy, dx slabs in f32 plus the bf16 originals (~5 f32-slab
# equivalents); keep comfortably under the ~16 MB/core budget so the
# pipelined double-buffering still fits.
VMEM_BUDGET_BYTES = 8 * 1024 * 1024
_F32_SLABS = 5  # worst-case resident f32 copies of one (hw, c_block) slab


def _pick_c_block(hw: int, c: int, cg: int):
    """Largest group-aligned channel slab that fits the VMEM budget.

    Doubles from one group's channels up (so every slab holds whole
    groups and ``c % c_block == 0``); None when even a single group's
    slab blows the budget."""
    if hw * cg * 4 * _F32_SLABS > VMEM_BUDGET_BYTES:
        return None
    blk = cg
    while (blk * 2 <= c and c % (blk * 2) == 0
           and hw * blk * 2 * 4 * _F32_SLABS <= VMEM_BUDGET_BYTES):
        blk *= 2
    return blk


def supports_fused(shape, num_groups: int) -> bool:
    """True when the fused kernel handles this NHWC shape in-budget."""
    if len(shape) != 4:
        return False
    n, h, w, c = shape
    if c % num_groups:
        return False
    return _pick_c_block(h * w, c, c // num_groups) is not None


def _group_matrix(c_block: int, groups_per_block: int):
    """[c_block, g_blk] one-hot group membership: matmul with it sums
    per-channel partials into per-group totals (and its transpose
    broadcasts per-group stats back per-channel) — no lane-crossing
    reshapes inside the kernel."""
    cg = c_block // groups_per_block
    ch = jnp.arange(c_block)[:, None]
    gr = jnp.arange(groups_per_block)[None, :]
    return (ch // cg == gr).astype(jnp.float32)


def _silu_grad(z, sig):
    # d silu(z)/dz with sig = sigmoid(z)
    return sig * (1.0 + z * (1.0 - sig))


def _gn_fwd_kernel(x_ref, gamma_ref, beta_ref, gmat_ref,
                   y_ref, mean_ref, rstd_ref, *, eps, act, inv_n):
    x = x_ref[0].astype(jnp.float32)          # [hw, c_blk]
    gmat = gmat_ref[...]                      # [c_blk, g_blk]
    # stable two-pass moments over the VMEM-resident slab
    mean_g = (jnp.sum(x, axis=0, keepdims=True) @ gmat) * inv_n  # [1, g_blk]
    mean_c = mean_g @ gmat.T                  # [1, c_blk]
    d = x - mean_c
    var_g = (jnp.sum(d * d, axis=0, keepdims=True) @ gmat) * inv_n
    rstd_g = jax.lax.rsqrt(var_g + eps)
    xhat = d * (rstd_g @ gmat.T)
    y = xhat * gamma_ref[...] + beta_ref[...]
    if act == "silu":
        y = y * jax.nn.sigmoid(y)
    y_ref[0] = y.astype(y_ref.dtype)
    mean_ref[...] = mean_g
    rstd_ref[...] = rstd_g


def _gn_bwd_kernel(x_ref, dy_ref, gamma_ref, beta_ref, gmat_ref,
                   mean_ref, rstd_ref,
                   dx_ref, dgamma_ref, dbeta_ref, *, act, inv_n):
    x = x_ref[0].astype(jnp.float32)          # [hw, c_blk]
    dy = dy_ref[0].astype(jnp.float32)
    gmat = gmat_ref[...]
    gamma = gamma_ref[...]                    # [1, c_blk]
    rstd_c = rstd_ref[...] @ gmat.T
    xhat = (x - mean_ref[...] @ gmat.T) * rstd_c
    dz = dy
    if act == "silu":
        z = xhat * gamma + beta_ref[...]
        sig = jax.nn.sigmoid(z)
        dz = dy * _silu_grad(z, sig)
    dgamma_ref[...] = jnp.sum(dz * xhat, axis=0, keepdims=True)[None]
    dbeta_ref[...] = jnp.sum(dz, axis=0, keepdims=True)[None]
    dxhat = dz * gamma
    m1 = (jnp.sum(dxhat, axis=0, keepdims=True) @ gmat) * inv_n
    m2 = (jnp.sum(dxhat * xhat, axis=0, keepdims=True) @ gmat) * inv_n
    dx = rstd_c * (dxhat - m1 @ gmat.T - xhat * (m2 @ gmat.T))
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _gn_fwd_pallas(x3, gamma, beta, num_groups, eps, act):
    """x3: [n, hw, c]. Returns (y [n, hw, c], mean [n, g], rstd [n, g])."""
    n, hw, c = x3.shape
    g = num_groups
    cg = c // g
    c_blk = _pick_c_block(hw, c, cg)
    g_blk = c_blk // cg
    gmat = _group_matrix(c_blk, g_blk)
    grid = (n, c // c_blk)
    kernel = functools.partial(_gn_fwd_kernel, eps=eps, act=act,
                               inv_n=1.0 / (hw * cg))
    f32 = jnp.float32
    return pl.pallas_call(
        kernel, grid=grid,
        in_specs=[
            pl.BlockSpec((1, hw, c_blk), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, c_blk), lambda i, j: (0, j)),
            pl.BlockSpec((1, c_blk), lambda i, j: (0, j)),
            pl.BlockSpec((c_blk, g_blk), lambda i, j: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, hw, c_blk), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, g_blk), lambda i, j: (i, j)),
            pl.BlockSpec((1, g_blk), lambda i, j: (i, j)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, hw, c), x3.dtype),
            jax.ShapeDtypeStruct((n, g), f32),
            jax.ShapeDtypeStruct((n, g), f32),
        ),
        interpret=_interpret(),
    )(x3, gamma.reshape(1, c).astype(f32), beta.reshape(1, c).astype(f32),
      gmat)


def _gn_bwd_pallas(x3, dy3, gamma, beta, mean, rstd, num_groups, act):
    n, hw, c = x3.shape
    g = num_groups
    cg = c // g
    c_blk = _pick_c_block(hw, c, cg)
    g_blk = c_blk // cg
    gmat = _group_matrix(c_blk, g_blk)
    grid = (n, c // c_blk)
    kernel = functools.partial(_gn_bwd_kernel, act=act,
                               inv_n=1.0 / (hw * cg))
    f32 = jnp.float32
    dx, dgam, dbeta = pl.pallas_call(
        kernel, grid=grid,
        in_specs=[
            pl.BlockSpec((1, hw, c_blk), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, hw, c_blk), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, c_blk), lambda i, j: (0, j)),
            pl.BlockSpec((1, c_blk), lambda i, j: (0, j)),
            pl.BlockSpec((c_blk, g_blk), lambda i, j: (0, 0)),
            pl.BlockSpec((1, g_blk), lambda i, j: (i, j)),
            pl.BlockSpec((1, g_blk), lambda i, j: (i, j)),
        ],
        out_specs=(
            pl.BlockSpec((1, hw, c_blk), lambda i, j: (i, 0, j)),
            # per-sample partials, reduced over n by the caller ([n, c]
            # f32 — noise next to the [n, hw, c] activations)
            pl.BlockSpec((1, 1, c_blk), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, 1, c_blk), lambda i, j: (i, 0, j)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, hw, c), x3.dtype),
            jax.ShapeDtypeStruct((n, 1, c), f32),
            jax.ShapeDtypeStruct((n, 1, c), f32),
        ),
        interpret=_interpret(),
    )(x3, dy3, gamma.reshape(1, c).astype(f32),
      beta.reshape(1, c).astype(f32), gmat, mean, rstd)
    return dx, dgam.sum(axis=(0, 1)), dbeta.sum(axis=(0, 1))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_group_norm3(x3, gamma, beta, num_groups, eps, act):
    y, _, _ = _gn_fwd_pallas(x3, gamma, beta, num_groups, eps, act)
    return y


def _fused_fwd(x3, gamma, beta, num_groups, eps, act):
    y, mean, rstd = _gn_fwd_pallas(x3, gamma, beta, num_groups, eps, act)
    return y, (x3, gamma, beta, mean, rstd)


def _fused_bwd(num_groups, eps, act, res, dy):
    x3, gamma, beta, mean, rstd = res
    dx, dgam, dbeta = _gn_bwd_pallas(
        x3, dy, gamma, beta, mean, rstd, num_groups, act)
    return (dx, dgam.astype(gamma.dtype), dbeta.astype(beta.dtype))


_fused_group_norm3.defvjp(_fused_fwd, _fused_bwd)


def fused_group_norm(x, gamma, beta, num_groups, epsilon=1e-5,
                     activation=None):
    """Fused GroupNorm(+activation) over NHWC ``x [n, h, w, c]``.

    gamma/beta: [c]. ``activation``: None | "silu" (applied INSIDE the
    kernel after the affine — the UNet's norm→SiLU chain as one HBM
    pass). Differentiable via the fused backward kernel. Shapes outside
    the kernel's budget (``supports_fused`` False) fall back to the lax
    reference — same numerics, no crash."""
    if activation not in (None, "silu"):
        raise ValueError(
            f"fused_group_norm: unknown activation {activation!r}")
    if not supports_fused(x.shape, num_groups):
        return group_norm_reference(x, gamma, beta, num_groups, epsilon,
                                    activation)
    n, h, w, c = x.shape
    y = _fused_group_norm3(x.reshape(n, h * w, c), gamma, beta,
                           int(num_groups), float(epsilon), activation)
    return y.reshape(n, h, w, c)


def group_norm_reference(x, gamma=None, beta=None, num_groups=1,
                         epsilon=1e-5, activation=None):
    """Pure-jnp NHWC GroupNorm(+activation) — the kernel's numeric
    source of truth and the over-budget fallback. Stats, affine, and
    activation all in f32 (matching the kernel), output in x.dtype.
    Still transpose-free: reductions run on the channels-last tensor
    directly."""
    n, c = x.shape[0], x.shape[-1]
    g = num_groups
    spatial = x.shape[1:-1]
    xf = x.astype(jnp.float32).reshape(n, -1, g, c // g)
    mean = jnp.mean(xf, axis=(1, 3), keepdims=True)
    d = xf - mean
    var = jnp.mean(d * d, axis=(1, 3), keepdims=True)
    y = d * jax.lax.rsqrt(var + epsilon)
    y = y.reshape(n, *spatial, c)
    if gamma is not None:
        y = y * gamma.astype(jnp.float32)
    if beta is not None:
        y = y + beta.astype(jnp.float32)
    if activation == "silu":
        y = y * jax.nn.sigmoid(y)
    elif activation is not None:
        raise ValueError(f"group_norm: unknown activation {activation!r}")
    return y.astype(x.dtype)
