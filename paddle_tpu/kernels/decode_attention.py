"""Fused single-pass decode attention for CONTIGUOUS per-slot KV caches
(+ the dispatch gate and lax reference paths shared with the paged
variant in ``paged_attention.py``).

Parity: phi ``masked_multihead_attention`` — the reference's single
fused decode op that rotates the new token, writes it into the cache and
attends, all in one kernel. The engine's default contiguous mode
previously paid three HBM round-trips per decoder layer per token
(RoPE materializes rotated q/k, the per-slot scatter writes K/V, dense
masked SDPA then re-reads ``[slots, max_len]`` including every padding
row); this kernel does all three in one pass with LENGTH-PRUNED
streaming — per-step traffic ∝ Σ ceil(len_i/chunk)·chunk, the same
``Σ seq_lens`` scaling the paged kernel already has, instead of
``slots × max_len``.

Structure (mirrors kernels/paged_attention.py):
  - the cache rides as ``[slots, max_len, kvh*d]`` (a free reshape of
    the engine's ``[slots, max_len, kvh, d]`` layout): the per-grid-step
    block is one slot's ``chunk`` rows with minor dims
    ``(chunk, kvh*d)`` — full tiled minor dims, no head-strided DMA —
    and all kv heads stream in one fetch, with a static per-head loop
    inside the kernel;
  - grid = (slots, n_chunks), chunks innermost; chunks past a slot's
    length are pruned (index map clamps → no DMA, pl.when skips
    compute);
  - RoPE is applied in-kernel from scalar-prefetched positions (the
    cos/sin table row is the block index — one row read per slot);
  - the new token's K/V is merged into the streamed chunk in VMEM and
    written back as ONE aliased row (``input_output_aliases``), so the
    token never round-trips through HBM before attention reads it and
    the separate append scatter disappears from the decode trace.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import flags
from ..jax_compat import tpu_compiler_params
from .paged_attention import (
    NEG_INF,
    _interpret,
    kernel_rope_rot,
    online_softmax_update,
)


def contiguous_chunk(max_len: int) -> int:
    """Streaming granularity over the [slots, max_len] cache rows:
    gcd(max_len, 128) — i.e. the largest power-of-two divisor of
    max_len capped at 128 — keeps blocks tile-aligned without
    constraining the engine's max_len choice."""
    return math.gcd(max_len, 128)


def decode_tiles_ok(head_dim: int, minor: int) -> bool:
    """THE tiling rule for every Pallas decode kernel (block-table and
    fused, both cache modes — ``inference.paged._use_pallas_decode``
    shares it): d fills the lane dim, and ``minor`` (page_size or the
    contiguous chunk) respects the bf16 sublane tile, so one rule
    covers both pool dtypes."""
    return head_dim % 128 == 0 and minor % 16 == 0


def fused_decode_active(head_dim: int, minor: int) -> bool:
    """Gate for the fused decode kernels (PT_FLAGS_fused_decode).

    ``minor``: page_size (paged mode) or the contiguous chunk length —
    the streamed block's sublane dim. auto = compiled kernel on TPU when
    the block tiles (``decode_tiles_ok``); the lax reference elsewhere.
    ``on`` forces the kernel (Pallas interpret mode off-TPU — how the
    tier-1 parity tests run it); ``off`` forces the reference path.
    """
    val = str(flags.flag("fused_decode")).lower()
    if val in ("off", "0", "false", "no"):
        return False
    if jax.default_backend() != "tpu":
        return val in ("on", "1", "true", "yes")
    if val in ("on", "1", "true", "yes"):
        return True
    return decode_tiles_ok(head_dim, minor)


# ---------------------------------------------------------------------------
# Pallas kernel — contiguous per-slot caches
# ---------------------------------------------------------------------------
def _fused_contig_kernel(lens_ref, pos_ref, q_ref, kn_ref, vn_ref,
                         k_ref, v_ref, cos_ref, sin_ref,
                         o_ref, ko_ref, vo_ref,
                         q_scratch, m_scratch, l_scratch, acc_scratch,
                         *, scale, chunk, n_chunks, kvh, d):
    s = pl.program_id(0)
    j = pl.program_id(1)
    seq_len = lens_ref[s]  # position of THIS token (== tokens cached)
    last_chunk = seq_len // chunk
    offs = seq_len % chunk

    cos = cos_ref[...].astype(jnp.float32)  # [1, d/2] row at pos_ref[s]
    sin = sin_ref[...].astype(jnp.float32)

    def rot(x):
        return kernel_rope_rot(x, cos, sin)

    # rotated new-token K for all heads, flattened to the cache row
    # layout [1, kvh*d]; written back as ONE aliased row per slot.
    # Attention merges the CACHE-DTYPE-ROUNDED values — same rounding
    # the unfused path's appended row gets — so bf16 caches cannot
    # flip a greedy argmax between the fused and unfused engines
    k_store = rot(kn_ref[...].astype(jnp.float32)) \
        .reshape(1, kvh * d).astype(ko_ref.dtype)
    v_store = vn_ref[...].reshape(1, kvh * d).astype(vo_ref.dtype)
    ko_ref[...] = k_store
    vo_ref[...] = v_store
    k_new = k_store.astype(jnp.float32)
    v_new = v_store.astype(jnp.float32)

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)
        q_scratch[:] = rot(q_ref[...].astype(jnp.float32))

    @pl.when(j <= last_chunk)
    def _step():
        is_last = j == last_chunk
        row = jax.lax.broadcasted_iota(jnp.int32, (chunk, 1), 0)
        sel = (row == offs) & is_last
        # merge the new token into the streamed chunk in VMEM
        k_blk = jnp.where(sel, k_new, k_ref[...].astype(jnp.float32))
        v_blk = jnp.where(sel, v_new, v_ref[...].astype(jnp.float32))
        valid = (j * chunk + jax.lax.broadcasted_iota(
            jnp.int32, (1, chunk), 1)) <= seq_len  # [1, chunk]
        for h in range(kvh):  # static unroll; all heads share the fetch
            kh = k_blk[:, h * d:(h + 1) * d]  # [chunk, d]
            vh = v_blk[:, h * d:(h + 1) * d]
            q = q_scratch[h]  # [group_pad, d] rotated f32
            sc = jax.lax.dot_general(
                q, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [group_pad, chunk]
            sc = jnp.where(valid, sc, NEG_INF)
            m_new, l_new, acc = online_softmax_update(
                sc, vh, m_scratch[h, :, :1], l_scratch[h, :, :1],
                acc_scratch[h])
            acc_scratch[h] = acc
            m_scratch[h] = jnp.broadcast_to(m_new, m_scratch.shape[1:])
            l_scratch[h] = jnp.broadcast_to(l_new, l_scratch.shape[1:])

    @pl.when(j == n_chunks - 1)
    def _fin():
        for h in range(kvh):
            l = l_scratch[h, :, :1]
            l = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, h] = (acc_scratch[h] / l).astype(o_ref.dtype)


def fused_contiguous_decode_attention(q, k_new, v_new, ck, cv, seq_lens,
                                      positions, cos, sin, scale=None):
    """Single-pass decode over the engine's contiguous per-slot caches:
    RoPE(q, k_new) + write (k_new, v_new) at each slot's current length
    + length-pruned online-softmax attention, one kernel per layer.

    q: [slots, kv_heads, group, d] UNROTATED; k_new/v_new:
    [slots, kv_heads, d]. ck/cv: [slots, max_len, kv_heads, d] — ALIASED
    into the outputs (donate under jit). seq_lens: [slots] int32 tokens
    already cached; slot i attends to [0, seq_lens[i]] inclusive of the
    appended token. positions: [slots] int32 RoPE positions. cos/sin:
    [max_pos, d//2].

    PRECONDITION (unchecked — indices are traced): seq_lens[i] <
    max_len (the cache has room for the appended row; Pallas CLAMPS
    out-of-range block indices, so violating this silently overwrites
    the last cached row) and positions[i] < cos.shape[0]. The serving
    engine guarantees both (add_request length check + _maybe_finish).

    Returns (out [slots, kv_heads, group, d], ck', cv').
    """
    slots, kvh, group, d = q.shape
    max_len = ck.shape[1]
    chunk = contiguous_chunk(max_len)
    n_chunks = max_len // chunk
    if scale is None:
        scale = d ** -0.5

    group_pad = max(8, -(-group // 8) * 8)
    if group_pad != group:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, group_pad - group), (0, 0)))
    k_new = k_new.reshape(slots, kvh, 1, d)
    v_new = v_new.reshape(slots, kvh, 1, d)
    # free layout view: one streamed block is (chunk, kvh*d) — full
    # tiled minor dims; a head-minor 4D block would DMA sublane-strided
    ck2 = ck.reshape(slots, max_len, kvh * d)
    cv2 = cv.reshape(slots, max_len, kvh * d)
    half = d // 2

    def q_index(s, j, lens_ref, pos_ref):
        return (s, 0, 0, 0)

    def kv_index(s, j, lens_ref, pos_ref):
        # clamp to the slot's last active chunk: pruned steps revisit
        # the previous block, so no DMA is issued for them
        return (s, jnp.minimum(j, lens_ref[s] // chunk), 0)

    def rope_index(s, j, lens_ref, pos_ref):
        return (pos_ref[s], 0)

    def append_index(s, j, lens_ref, pos_ref):
        return (s, lens_ref[s], 0)  # the new token's row, constant in j

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, n_chunks),
        in_specs=[
            pl.BlockSpec((None, kvh, group_pad, d),
                         lambda s, j, l, p: (s, 0, 0, 0)),
            pl.BlockSpec((None, kvh, 1, d),
                         lambda s, j, l, p: (s, 0, 0, 0)),
            pl.BlockSpec((None, kvh, 1, d),
                         lambda s, j, l, p: (s, 0, 0, 0)),
            pl.BlockSpec((None, chunk, kvh * d), kv_index),
            pl.BlockSpec((None, chunk, kvh * d), kv_index),
            pl.BlockSpec((1, half), rope_index),
            pl.BlockSpec((1, half), rope_index),
        ],
        out_specs=[
            pl.BlockSpec((1, kvh, group_pad, d), q_index),
            pl.BlockSpec((None, 1, kvh * d), append_index),
            pl.BlockSpec((None, 1, kvh * d), append_index),
        ],
        scratch_shapes=[
            pltpu.VMEM((kvh, group_pad, d), jnp.float32),
            pltpu.VMEM((kvh, group_pad, 128), jnp.float32),
            pltpu.VMEM((kvh, group_pad, 128), jnp.float32),
            pltpu.VMEM((kvh, group_pad, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _fused_contig_kernel, scale=scale, chunk=chunk,
        n_chunks=n_chunks, kvh=kvh, d=d,
    )
    out, ck2, cv2 = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((slots, kvh, group_pad, d), q.dtype),
            jax.ShapeDtypeStruct(ck2.shape, ck2.dtype),
            jax.ShapeDtypeStruct(cv2.shape, cv2.dtype),
        ],
        # operand order: 2 prefetch scalars, q, kn, vn, ck(5), cv(6),
        # cos, sin — caches alias outputs 1/2 (in-place append)
        input_output_aliases={5: 1, 6: 2},
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(jnp.asarray(seq_lens, jnp.int32),
      jnp.asarray(positions, jnp.int32),
      q, k_new, v_new, ck2, cv2, cos, sin)
    return (out[:, :, :group, :],
            ck2.reshape(slots, max_len, kvh, d),
            cv2.reshape(slots, max_len, kvh, d))


# ---------------------------------------------------------------------------
# lax reference paths (numeric source of truth for parity tests)
# ---------------------------------------------------------------------------
def _rope_rotate(x, positions, cos, sin):
    """x: [slots, heads, d] (one token per slot) → rotated via the
    canonical ``kernels/rope.apply_rope`` (so the oracle can never
    drift from the model path's rope convention)."""
    from .rope import apply_rope

    x4 = x[:, None]  # [slots, 1, heads, d]
    out, _ = apply_rope(x4, x4, cos, sin, positions[:, None])
    return out[:, 0]


def fused_paged_decode_reference(q, k_new, v_new, k_pages, v_pages,
                                 block_tables, seq_lens, positions,
                                 cos, sin, scale=None):
    """Unfused reference for ``fused_paged_decode_attention``: rope →
    append_kv scatter → dense gathered attention (the pre-fusion decode
    path, kept as the parity oracle)."""
    from ..inference.paged import (
        PagedLayerCache,
        PagedState,
        append_kv,
        dense_paged_attention,
    )

    slots, kvh, group, d = q.shape
    qr = _rope_rotate(q.reshape(slots, kvh * group, d), positions,
                      cos, sin).reshape(slots, kvh, group, d)
    kr = _rope_rotate(k_new, positions, cos, sin)
    cache = PagedLayerCache(k_pages, v_pages)
    state = PagedState(jnp.asarray(block_tables, jnp.int32),
                       jnp.asarray(seq_lens, jnp.int32))
    cache = append_kv(cache, state, kr[:, None], v_new[:, None])
    out = dense_paged_attention(
        qr.reshape(slots, 1, kvh * group, d), cache, state, scale=scale)
    return (out[:, 0].reshape(slots, kvh, group, d),
            cache.k_pages, cache.v_pages)


def fused_contiguous_decode_reference(q, k_new, v_new, ck, cv, seq_lens,
                                      positions, cos, sin, scale=None):
    """Unfused reference for ``fused_contiguous_decode_attention``:
    rope → per-slot scatter → dense masked attention over the full
    [slots, max_len] cache (the pre-fusion contiguous decode path)."""
    slots, kvh, group, d = q.shape
    max_len = ck.shape[1]
    if scale is None:
        scale = d ** -0.5
    qr = _rope_rotate(q.reshape(slots, kvh * group, d), positions,
                      cos, sin).reshape(slots, kvh, group, d)
    kr = _rope_rotate(k_new, positions, cos, sin)
    lens = jnp.asarray(seq_lens, jnp.int32)
    ck = ck.at[jnp.arange(slots), lens].set(kr.astype(ck.dtype))
    cv = cv.at[jnp.arange(slots), lens].set(v_new.astype(cv.dtype))
    k = jnp.repeat(ck.astype(jnp.float32), group, axis=2)
    v = jnp.repeat(cv.astype(jnp.float32), group, axis=2)
    qf = qr.reshape(slots, kvh * group, 1, d).astype(jnp.float32) * scale
    s = jnp.einsum("shqd,skhd->shqk", qf, k)
    mask = jnp.arange(max_len)[None, :] <= lens[:, None]
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("shqk,skhd->shqd", p, v)
    return (out[:, :, 0].reshape(slots, kvh, group, d).astype(q.dtype),
            ck, cv)
