"""Fused single-pass decode attention for CONTIGUOUS per-slot KV caches
(+ the dispatch gate and lax reference paths shared with the paged
variant in ``paged_attention.py``).

Parity: phi ``masked_multihead_attention`` — the reference's single
fused decode op that rotates the new token, writes it into the cache and
attends, all in one kernel. The engine's default contiguous mode
previously paid three HBM round-trips per decoder layer per token
(RoPE materializes rotated q/k, the per-slot scatter writes K/V, dense
masked SDPA then re-reads ``[slots, max_len]`` including every padding
row); this kernel does all three in one pass with LENGTH-PRUNED
streaming — per-step traffic ∝ Σ ceil(len_i/chunk)·chunk, the same
``Σ seq_lens`` scaling the paged kernel already has, instead of
``slots × max_len``.

Structure (mirrors kernels/paged_attention.py):
  - the cache rides as ``[slots, max_len, kvh*d]`` (a free reshape of
    the engine's ``[slots, max_len, kvh, d]`` layout): the per-grid-step
    block is one slot's ``chunk`` rows with minor dims
    ``(chunk, kvh*d)`` — full tiled minor dims, no head-strided DMA —
    and all kv heads stream in one fetch, with a static per-head loop
    inside the kernel;
  - grid = (slots, n_chunks), chunks innermost; chunks past a slot's
    length are pruned (index map clamps → no DMA, pl.when skips
    compute);
  - RoPE is applied in-kernel from scalar-prefetched positions (the
    cos/sin table row is the block index — one row read per slot);
  - the new token's K/V is merged into the streamed chunk in VMEM and
    written back as ONE aliased row (``input_output_aliases``), so the
    token never round-trips through HBM before attention reads it and
    the separate append scatter disappears from the decode trace.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import flags
from ..jax_compat import tpu_compiler_params
from .paged_attention import (
    NEG_INF,
    _interpret,
    kernel_quant_rows,
    kernel_rope_rot,
    online_softmax_update,
)


# ---------------------------------------------------------------------------
# ptaudit contract annotation (analysis/program_audit.py imports this):
# the dtype widenings the decode-path kernels PROMISE — narrow streams
# (bf16/f16 caches, int8/int4 payloads and weight groups) stay narrow
# through HBM and widen only at these in-register sites. Any other
# narrow->wide convert inside a compiled serving program is a DQ001
# finding, because it silently re-widens the stream the bytes-per-token
# models (kernelbench) price as narrow.
# ---------------------------------------------------------------------------
AUDIT_WIDEN_ALLOW = {
    "bfloat16->float32": "attention gathers bf16 K/V rows and "
                         "accumulates logits/softmax in f32 in-VMEM "
                         "(never re-materialized wide to HBM)",
    "float16->float32": "same softmax-accumulator discipline for f16 "
                        "caches",
    "int8->float32": "in-kernel dequant: int8 KV payloads / weight "
                     "groups widen against their f32 scale rows only "
                     "at the matmul/attention input",
}


def contiguous_chunk(max_len: int) -> int:
    """Streaming granularity over the [slots, max_len] cache rows:
    gcd(max_len, 128) — i.e. the largest power-of-two divisor of
    max_len capped at 128 — keeps blocks tile-aligned without
    constraining the engine's max_len choice."""
    return math.gcd(max_len, 128)


def decode_tiles_ok(head_dim: int, minor: int, dtype=None) -> bool:
    """THE tiling rule for every Pallas decode kernel (block-table and
    fused, both cache modes — ``inference.paged._use_pallas_decode``
    shares it): d fills the lane dim, and ``minor`` (page_size or the
    contiguous chunk) respects the pool dtype's sublane tile — 16 for
    the bf16/f32 pools, 32 for int8 (the int8 min tile is (32, 128))."""
    sub = 32 if (dtype is not None
                 and jnp.dtype(dtype) == jnp.int8) else 16
    return head_dim % 128 == 0 and minor % sub == 0


def fused_decode_active(head_dim: int, minor: int, dtype=None) -> bool:
    """Gate for the fused decode kernels (PT_FLAGS_fused_decode).

    ``minor``: page_size (paged mode) or the contiguous chunk length —
    the streamed block's sublane dim; ``dtype``: the pool dtype (int8
    tightens the tile rule). auto = compiled kernel on TPU when the
    block tiles (``decode_tiles_ok``); the lax reference elsewhere.
    ``on`` forces the kernel (Pallas interpret mode off-TPU — how the
    tier-1 parity tests run it); ``off`` forces the reference path.
    """
    val = str(flags.flag("fused_decode")).lower()
    if val in ("off", "0", "false", "no"):
        return False
    if jax.default_backend() != "tpu":
        return val in ("on", "1", "true", "yes")
    if val in ("on", "1", "true", "yes"):
        return True
    return decode_tiles_ok(head_dim, minor, dtype)


# ---------------------------------------------------------------------------
# Pallas kernel — contiguous per-slot caches
# ---------------------------------------------------------------------------
def _fused_contig_kernel(lens_ref, pos_ref, q_ref, kn_ref, vn_ref,
                         k_ref, v_ref, *rest,
                         scale, chunk, n_chunks, kvh, d, quant):
    if quant:
        (ks_ref, vs_ref, cos_ref, sin_ref, o_ref, ko_ref, vo_ref,
         kso_ref, vso_ref, q_scratch, m_scratch, l_scratch,
         acc_scratch) = rest
    else:
        (cos_ref, sin_ref, o_ref, ko_ref, vo_ref, q_scratch,
         m_scratch, l_scratch, acc_scratch) = rest
    s = pl.program_id(0)
    j = pl.program_id(1)
    seq_len = lens_ref[s]  # position of THIS token (== tokens cached)
    last_chunk = seq_len // chunk
    offs = seq_len % chunk

    cos = cos_ref[...].astype(jnp.float32)  # [1, d/2] row at pos_ref[s]
    sin = sin_ref[...].astype(jnp.float32)

    def rot(x):
        return kernel_rope_rot(x, cos, sin)

    # rotated new-token K for all heads, flattened to the cache row
    # layout [1, kvh*d]; written back as ONE aliased row per slot.
    # Attention merges the CACHE-DTYPE-ROUNDED values — same rounding
    # the unfused path's appended row gets — so bf16/int8 caches cannot
    # flip a greedy argmax between the fused and unfused engines
    k_rot = rot(kn_ref[...].astype(jnp.float32))  # [kvh, 1, d]
    v_raw = vn_ref[...].astype(jnp.float32)
    if quant:
        # quantize-on-append in-kernel (per head over d — the same row
        # rule as inference.paged.quantize_kv_rows): int8 payload to
        # the cache row, f32 scales to the [1, kvh] scale row
        kq, kscl = kernel_quant_rows(k_rot)   # [kvh, 1, d], [kvh, 1, 1]
        vq, vscl = kernel_quant_rows(v_raw)
        ko_ref[...] = kq.reshape(1, kvh * d)
        vo_ref[...] = vq.reshape(1, kvh * d)
        kso_ref[...] = kscl.reshape(1, kvh)
        vso_ref[...] = vscl.reshape(1, kvh)
        k_new = (kq.astype(jnp.float32) * kscl).reshape(1, kvh * d)
        v_new = (vq.astype(jnp.float32) * vscl).reshape(1, kvh * d)
    else:
        k_store = k_rot.reshape(1, kvh * d).astype(ko_ref.dtype)
        v_store = v_raw.reshape(1, kvh * d).astype(vo_ref.dtype)
        ko_ref[...] = k_store
        vo_ref[...] = v_store
        k_new = k_store.astype(jnp.float32)
        v_new = v_store.astype(jnp.float32)

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)
        q_scratch[:] = rot(q_ref[...].astype(jnp.float32))

    @pl.when(j <= last_chunk)
    def _step():
        is_last = j == last_chunk
        row = jax.lax.broadcasted_iota(jnp.int32, (chunk, 1), 0)
        sel = (row == offs) & is_last
        kf = k_ref[...].astype(jnp.float32)
        vf = v_ref[...].astype(jnp.float32)
        if quant:
            # dequantize the streamed chunk: scale rows [chunk, kvh]
            # broadcast over each head's d-segment of the row layout
            kf = kf * jnp.repeat(ks_ref[...], d, axis=1)
            vf = vf * jnp.repeat(vs_ref[...], d, axis=1)
        # merge the new token into the streamed chunk in VMEM
        k_blk = jnp.where(sel, k_new, kf)
        v_blk = jnp.where(sel, v_new, vf)
        valid = (j * chunk + jax.lax.broadcasted_iota(
            jnp.int32, (1, chunk), 1)) <= seq_len  # [1, chunk]
        for h in range(kvh):  # static unroll; all heads share the fetch
            kh = k_blk[:, h * d:(h + 1) * d]  # [chunk, d]
            vh = v_blk[:, h * d:(h + 1) * d]
            q = q_scratch[h]  # [group_pad, d] rotated f32
            sc = jax.lax.dot_general(
                q, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [group_pad, chunk]
            sc = jnp.where(valid, sc, NEG_INF)
            m_new, l_new, acc = online_softmax_update(
                sc, vh, m_scratch[h, :, :1], l_scratch[h, :, :1],
                acc_scratch[h])
            acc_scratch[h] = acc
            m_scratch[h] = jnp.broadcast_to(m_new, m_scratch.shape[1:])
            l_scratch[h] = jnp.broadcast_to(l_new, l_scratch.shape[1:])

    @pl.when(j == n_chunks - 1)
    def _fin():
        for h in range(kvh):
            l = l_scratch[h, :, :1]
            l = jnp.where(l == 0.0, 1.0, l)
            o_ref[0, h] = (acc_scratch[h] / l).astype(o_ref.dtype)


def fused_contiguous_decode_attention(q, k_new, v_new, ck, cv, seq_lens,
                                      positions, cos, sin, scale=None,
                                      k_scale=None, v_scale=None):
    """Single-pass decode over the engine's contiguous per-slot caches:
    RoPE(q, k_new) + write (k_new, v_new) at each slot's current length
    + length-pruned online-softmax attention, one kernel per layer.

    q: [slots, kv_heads, group, d] UNROTATED; k_new/v_new:
    [slots, kv_heads, d]. ck/cv: [slots, max_len, kv_heads, d] — ALIASED
    into the outputs (donate under jit). seq_lens: [slots] int32 tokens
    already cached; slot i attends to [0, seq_lens[i]] inclusive of the
    appended token. positions: [slots] int32 RoPE positions. cos/sin:
    [max_pos, d//2].

    PRECONDITION (unchecked — indices are traced): seq_lens[i] <
    max_len (the cache has room for the appended row; Pallas CLAMPS
    out-of-range block indices, so violating this silently overwrites
    the last cached row) and positions[i] < cos.shape[0]. The serving
    engine guarantees both (add_request length check + _maybe_finish).

    INT8 CACHES: pass ``k_scale``/``v_scale`` f32
    [slots, max_len, kvh] per-row dequant scales (the layout
    ``QuantizedKV`` carries). The kernel quantizes the appended row
    per head in-kernel (same absmax rule as the XLA scatter paths),
    writes payload + scale rows together, and dequantizes each
    streamed chunk in VMEM. Scale blocks are (chunk, kvh) — sublane
    matches the cache blocks, lane is the full kvh dim.

    Returns (out [slots, kv_heads, group, d], ck', cv') — plus
    (k_scale', v_scale') when quantized.
    """
    slots, kvh, group, d = q.shape
    max_len = ck.shape[1]
    chunk = contiguous_chunk(max_len)
    n_chunks = max_len // chunk
    quant = k_scale is not None
    if scale is None:
        scale = d ** -0.5

    group_pad = max(8, -(-group // 8) * 8)
    if group_pad != group:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, group_pad - group), (0, 0)))
    k_new = k_new.reshape(slots, kvh, 1, d)
    v_new = v_new.reshape(slots, kvh, 1, d)
    # free layout view: one streamed block is (chunk, kvh*d) — full
    # tiled minor dims; a head-minor 4D block would DMA sublane-strided
    ck2 = ck.reshape(slots, max_len, kvh * d)
    cv2 = cv.reshape(slots, max_len, kvh * d)
    half = d // 2

    def q_index(s, j, lens_ref, pos_ref):
        return (s, 0, 0, 0)

    def kv_index(s, j, lens_ref, pos_ref):
        # clamp to the slot's last active chunk: pruned steps revisit
        # the previous block, so no DMA is issued for them
        return (s, jnp.minimum(j, lens_ref[s] // chunk), 0)

    def rope_index(s, j, lens_ref, pos_ref):
        return (pos_ref[s], 0)

    def append_index(s, j, lens_ref, pos_ref):
        return (s, lens_ref[s], 0)  # the new token's row, constant in j

    in_specs = [
        pl.BlockSpec((None, kvh, group_pad, d),
                     lambda s, j, l, p: (s, 0, 0, 0)),
        pl.BlockSpec((None, kvh, 1, d),
                     lambda s, j, l, p: (s, 0, 0, 0)),
        pl.BlockSpec((None, kvh, 1, d),
                     lambda s, j, l, p: (s, 0, 0, 0)),
        pl.BlockSpec((None, chunk, kvh * d), kv_index),
        pl.BlockSpec((None, chunk, kvh * d), kv_index),
    ]
    out_specs = [
        pl.BlockSpec((1, kvh, group_pad, d), q_index),
        pl.BlockSpec((None, 1, kvh * d), append_index),
        pl.BlockSpec((None, 1, kvh * d), append_index),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((slots, kvh, group_pad, d), q.dtype),
        jax.ShapeDtypeStruct(ck2.shape, ck2.dtype),
        jax.ShapeDtypeStruct(cv2.shape, cv2.dtype),
    ]
    # operand order: 2 prefetch scalars, q, kn, vn, ck(5), cv(6),
    # [ks(7), vs(8),] cos, sin — caches (and scale arrays) alias
    # their outputs (in-place append)
    aliases = {5: 1, 6: 2}
    operands = [q, k_new, v_new, ck2, cv2]
    if quant:
        in_specs += [
            pl.BlockSpec((None, chunk, kvh), kv_index),
            pl.BlockSpec((None, chunk, kvh), kv_index),
        ]
        out_specs += [
            pl.BlockSpec((None, 1, kvh), append_index),
            pl.BlockSpec((None, 1, kvh), append_index),
        ]
        out_shape += [
            jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
            jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
        ]
        aliases.update({7: 3, 8: 4})
        operands += [k_scale, v_scale]
    in_specs += [
        pl.BlockSpec((1, half), rope_index),
        pl.BlockSpec((1, half), rope_index),
    ]
    operands += [cos, sin]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, n_chunks),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((kvh, group_pad, d), jnp.float32),
            pltpu.VMEM((kvh, group_pad, 128), jnp.float32),
            pltpu.VMEM((kvh, group_pad, 128), jnp.float32),
            pltpu.VMEM((kvh, group_pad, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _fused_contig_kernel, scale=scale, chunk=chunk,
        n_chunks=n_chunks, kvh=kvh, d=d, quant=quant,
    )
    res = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(jnp.asarray(seq_lens, jnp.int32),
      jnp.asarray(positions, jnp.int32),
      *operands)
    if quant:
        out, ck2, cv2, k_scale, v_scale = res
        return (out[:, :, :group, :],
                ck2.reshape(slots, max_len, kvh, d),
                cv2.reshape(slots, max_len, kvh, d),
                k_scale, v_scale)
    out, ck2, cv2 = res
    return (out[:, :, :group, :],
            ck2.reshape(slots, max_len, kvh, d),
            cv2.reshape(slots, max_len, kvh, d))


# ---------------------------------------------------------------------------
# lax reference paths (numeric source of truth for parity tests)
# ---------------------------------------------------------------------------
def _rope_rotate(x, positions, cos, sin):
    """x: [slots, heads, d] (one token per slot) → rotated via the
    canonical ``kernels/rope.apply_rope`` (so the oracle can never
    drift from the model path's rope convention)."""
    from .rope import apply_rope

    x4 = x[:, None]  # [slots, 1, heads, d]
    out, _ = apply_rope(x4, x4, cos, sin, positions[:, None])
    return out[:, 0]


def fused_paged_decode_reference(q, k_new, v_new, k_pages, v_pages,
                                 block_tables, seq_lens, positions,
                                 cos, sin, scale=None,
                                 k_scale=None, v_scale=None):
    """Unfused reference for ``fused_paged_decode_attention``: rope →
    append_kv scatter → dense gathered attention (the pre-fusion decode
    path, kept as the parity oracle). int8 pools (``k_scale`` set) ride
    the same path: ``append_kv`` quantizes-on-append, ``gather_kv``
    dequantizes, so this stays the numeric oracle for the quantized
    kernel too."""
    from ..inference.paged import (
        PagedLayerCache,
        PagedState,
        append_kv,
        dense_paged_attention,
    )

    slots, kvh, group, d = q.shape
    qr = _rope_rotate(q.reshape(slots, kvh * group, d), positions,
                      cos, sin).reshape(slots, kvh, group, d)
    kr = _rope_rotate(k_new, positions, cos, sin)
    cache = PagedLayerCache(k_pages, v_pages, k_scale, v_scale)
    state = PagedState(jnp.asarray(block_tables, jnp.int32),
                       jnp.asarray(seq_lens, jnp.int32))
    cache = append_kv(cache, state, kr[:, None], v_new[:, None])
    out = dense_paged_attention(
        qr.reshape(slots, 1, kvh * group, d), cache, state, scale=scale)
    out = out[:, 0].reshape(slots, kvh, group, d)
    if k_scale is not None:
        return (out, cache.k_pages, cache.v_pages,
                cache.k_scale, cache.v_scale)
    return out, cache.k_pages, cache.v_pages


def fused_contiguous_decode_reference(q, k_new, v_new, ck, cv, seq_lens,
                                      positions, cos, sin, scale=None,
                                      k_scale=None, v_scale=None):
    """Unfused reference for ``fused_contiguous_decode_attention``:
    rope → per-slot scatter → dense masked attention over the full
    [slots, max_len] cache (the pre-fusion contiguous decode path).
    int8 caches (``k_scale`` set): the appended row is quantized with
    the shared absmax rule and attention reads the dequantized cache."""
    from ..inference.paged import quantize_kv_rows

    slots, kvh, group, d = q.shape
    max_len = ck.shape[1]
    if scale is None:
        scale = d ** -0.5
    qr = _rope_rotate(q.reshape(slots, kvh * group, d), positions,
                      cos, sin).reshape(slots, kvh, group, d)
    kr = _rope_rotate(k_new, positions, cos, sin)
    lens = jnp.asarray(seq_lens, jnp.int32)
    quant = k_scale is not None
    if quant:
        kq, ks = quantize_kv_rows(kr)      # [slots, kvh, d] / [s, kvh]
        vq, vs = quantize_kv_rows(v_new)
        ck = ck.at[jnp.arange(slots), lens].set(kq)
        cv = cv.at[jnp.arange(slots), lens].set(vq)
        k_scale = k_scale.at[jnp.arange(slots), lens].set(ks)
        v_scale = v_scale.at[jnp.arange(slots), lens].set(vs)
        kf = ck.astype(jnp.float32) * k_scale[..., None]
        vf = cv.astype(jnp.float32) * v_scale[..., None]
    else:
        ck = ck.at[jnp.arange(slots), lens].set(kr.astype(ck.dtype))
        cv = cv.at[jnp.arange(slots), lens].set(v_new.astype(cv.dtype))
        kf, vf = ck, cv
    k = jnp.repeat(kf.astype(jnp.float32), group, axis=2)
    v = jnp.repeat(vf.astype(jnp.float32), group, axis=2)
    qf = qr.reshape(slots, kvh * group, 1, d).astype(jnp.float32) * scale
    s = jnp.einsum("shqd,skhd->shqk", qf, k)
    mask = jnp.arange(max_len)[None, :] <= lens[:, None]
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("shqk,skhd->shqd", p, v)
    out = out[:, :, 0].reshape(slots, kvh, group, d).astype(q.dtype)
    if quant:
        return out, ck, cv, k_scale, v_scale
    return out, ck, cv
