"""Pallas chunked selective scan (S6 linear recurrence) for Mamba.

Parity: the reference's selective-scan CUDA kernel (the "Mamba-2 / RWKV
selective-scan + linear-recurrence Phi op" BASELINE.json config).

Why a kernel when ``jax.lax.associative_scan`` already runs on TPU: the
associative formulation materializes the discretized operands
``dA, dBu`` — two ``[b, s, d, n]`` f32 tensors, a ``2n``-fold blowup of
the activations — and streams them through HBM O(log s) times. This
kernel never forms them: the sequence is processed in chunks with the
``[n, d]`` recurrent state resident in VMEM scratch across the
(sequential) chunk grid dimension, so HBM traffic is just the
``[b, s, d]``/``[b, s, n]`` inputs once and the output once — the same
streaming structure the reference's CUDA scan uses, mapped onto the
Pallas grid. Layout: state is kept ``[n, d]`` with d on lanes (n is
small, e.g. 16), so every VPU op runs full-width.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _scan_kernel(u_ref, delta_ref, b_ref, c_ref, at_ref, y_ref, h_scratch,
                 *, chunk):
    ic = pl.program_id(2)

    @pl.when(ic == 0)
    def _reset():
        h_scratch[:] = jnp.zeros_like(h_scratch)

    at = at_ref[...]  # [n, d_block]

    def body(t, h):
        # all [n, d] with d on lanes
        dt = delta_ref[0, t][None, :]          # [1, d]
        da = jnp.exp(dt * at)                  # [n, d]
        dbu = (dt * u_ref[0, t][None, :]) * b_ref[0, t][:, None]
        h = da * h + dbu
        y = jnp.sum(h * c_ref[0, t][:, None], axis=0)  # [d]
        y_ref[0, t] = y.astype(y_ref.dtype)
        return h

    h_scratch[:] = jax.lax.fori_loop(0, chunk, body, h_scratch[...])


def associative_selective_scan(u, delta, A, B, C, D):
    """Reference S6 scan via ``jax.lax.associative_scan``.

    u: [b,s,d]; delta: [b,s,d] (softplus-activated); A: [d,n] (negative);
    B, C: [b,s,n]; D: [d]. The combine (a,b)∘(a',b') = (a·a', a'·b+b')
    is associative, so XLA lowers a log-depth scan — but it materializes
    the [b,s,d,n] discretized operands in HBM, which is what the Pallas
    kernel below avoids. Also serves as the backward path for the
    kernel (the VJP of a linear recurrence is itself a scan XLA handles
    well).
    """
    dA = jnp.exp(delta[..., None] * A[None, None])
    dBu = (delta * u)[..., None] * B[:, :, None, :]

    def combine(x, y):
        a1, b1 = x
        a2, b2 = y
        return a2 * a1, a2 * b1 + b2

    _, h_all = jax.lax.associative_scan(combine, (dA, dBu), axis=1)
    y = jnp.einsum("bsdn,bsn->bsd", h_all, C)
    return y + u * D[None, None]


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def _chunked_scan(u, delta, A, B, C, D, chunk, d_block):
    b, s, d = u.shape
    n = A.shape[1]
    grid = (b, d // d_block, s // chunk)
    f32 = jnp.float32
    y = pl.pallas_call(
        functools.partial(_scan_kernel, chunk=chunk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, chunk, d_block), lambda ib, id_, ic: (ib, ic, id_)),
            pl.BlockSpec((1, chunk, d_block), lambda ib, id_, ic: (ib, ic, id_)),
            pl.BlockSpec((1, chunk, n), lambda ib, id_, ic: (ib, ic, 0)),
            pl.BlockSpec((1, chunk, n), lambda ib, id_, ic: (ib, ic, 0)),
            pl.BlockSpec((n, d_block), lambda ib, id_, ic: (0, id_)),
        ],
        out_specs=pl.BlockSpec(
            (1, chunk, d_block), lambda ib, id_, ic: (ib, ic, id_)),
        out_shape=jax.ShapeDtypeStruct((b, s, d), f32),
        scratch_shapes=[pltpu.VMEM((n, d_block), f32)],
        interpret=_interpret(),
    )(u.astype(f32), delta.astype(f32), B.astype(f32), C.astype(f32),
      A.T.astype(f32))
    return y + u.astype(f32) * D[None, None].astype(f32)


def _chunked_fwd(u, delta, A, B, C, D, chunk, d_block):
    return _chunked_scan(u, delta, A, B, C, D, chunk, d_block), \
        (u, delta, A, B, C, D)


def _chunked_bwd(chunk, d_block, res, g):
    # backward through the mathematically-identical associative form —
    # the recurrence VJP is itself a scan, which XLA lowers well; the
    # HBM saving matters most for inference/long-context forward passes
    _, vjp = jax.vjp(associative_selective_scan, *res)
    return vjp(g)


_chunked_scan.defvjp(_chunked_fwd, _chunked_bwd)


@functools.partial(jax.jit, static_argnames=("chunk", "d_block"))
def chunked_selective_scan(u, delta, A, B, C, D, *, chunk=128,
                           d_block=None):
    """y[b,s,d] for h_t = exp(Δ_t A)·h_{t-1} + Δ_t u_t B_t, y_t = C_t·h_t
    (+ u·D skip). Shapes as ``associative_selective_scan``."""
    b, s, d = u.shape
    if d_block is None:
        d_block = d if d <= 512 else 256
    if s % chunk:
        raise ValueError(f"seq len {s} not divisible by chunk {chunk}")
    if d % d_block:
        raise ValueError(f"d {d} not divisible by d_block {d_block}")
    return _chunked_scan(u, delta, A, B, C, D, chunk, d_block)
