"""Pallas chunked selective scan (S6 linear recurrence) for Mamba.

Parity: the reference's selective-scan CUDA kernel (the "Mamba-2 / RWKV
selective-scan + linear-recurrence Phi op" BASELINE.json config).

Why a kernel when ``jax.lax.associative_scan`` already runs on TPU: the
associative formulation materializes the discretized operands
``dA, dBu`` — two ``[b, s, d, n]`` f32 tensors, a ``2n``-fold blowup of
the activations — and streams them through HBM O(log s) times. This
kernel never forms them: the sequence is processed in chunks with the
``[n, d]`` recurrent state resident in VMEM scratch across the
(sequential) chunk grid dimension, so HBM traffic is just the
``[b, s, d]``/``[b, s, n]`` inputs once and the output once — the same
streaming structure the reference's CUDA scan uses, mapped onto the
Pallas grid. Layout: state is kept ``[n, d]`` with d on lanes (n is
small, e.g. 16), so every VPU op runs full-width.

Backward (recompute-based, like the reference CUDA bwd): the forward
additionally saves the recurrent state at each chunk BOUNDARY —
``[b, s/chunk, n, d]``, a ``chunk``-fold reduction vs ``[b, s, d, n]``.
The backward kernel walks chunks in reverse; within a chunk it first
re-runs the forward recurrence from the saved boundary state (states
live in a VMEM scratch, never HBM), then runs the reverse-time
cotangent recurrence  gh_{t} = C_t⊗g_t + dA_{t+1}·gh_{t+1}  emitting
du/dδ/dB/dC in place and accumulating dA in scratch. No ``[b, s, d, n]``
tensor exists in either pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _scan_kernel(u_ref, delta_ref, b_ref, c_ref, at_ref, *out_refs,
                 chunk, with_states):
    if with_states:
        y_ref, h0_ref, h_scratch = out_refs
    else:
        y_ref, h_scratch = out_refs
        h0_ref = None
    ic = pl.program_id(2)

    @pl.when(ic == 0)
    def _reset():
        h_scratch[:] = jnp.zeros_like(h_scratch)

    if h0_ref is not None:
        # state entering this chunk (end of previous chunk) — the
        # backward's recompute anchor
        h0_ref[0, 0] = h_scratch[...]

    at = at_ref[...]  # [n, d_block]

    def body(t, h):
        # all [n, d] with d on lanes
        dt = delta_ref[0, t][None, :]          # [1, d]
        da = jnp.exp(dt * at)                  # [n, d]
        dbu = (dt * u_ref[0, t][None, :]) * b_ref[0, t][:, None]
        h = da * h + dbu
        y = jnp.sum(h * c_ref[0, t][:, None], axis=0)  # [d]
        y_ref[0, t] = y.astype(y_ref.dtype)
        return h

    h_scratch[:] = jax.lax.fori_loop(0, chunk, body, h_scratch[...])


def associative_selective_scan(u, delta, A, B, C, D):
    """Reference S6 scan via ``jax.lax.associative_scan``.

    u: [b,s,d]; delta: [b,s,d] (softplus-activated); A: [d,n] (negative);
    B, C: [b,s,n]; D: [d]. The combine (a,b)∘(a',b') = (a·a', a'·b+b')
    is associative, so XLA lowers a log-depth scan — but it materializes
    the [b,s,d,n] discretized operands in HBM, which is what the Pallas
    kernel below avoids (in both passes). Kept as the numeric reference
    for the kernel's tests.
    """
    dA = jnp.exp(delta[..., None] * A[None, None])
    dBu = (delta * u)[..., None] * B[:, :, None, :]

    def combine(x, y):
        a1, b1 = x
        a2, b2 = y
        return a2 * a1, a2 * b1 + b2

    _, h_all = jax.lax.associative_scan(combine, (dA, dBu), axis=1)
    y = jnp.einsum("bsdn,bsn->bsd", h_all, C)
    return y + u * D[None, None]


def _scan_fwd_pallas(u, delta, B, C, at, chunk, d_block, with_states):
    """Run the forward kernel. Returns y (and chunk-boundary states when
    ``with_states``). ``at`` is A.T ([n, d]) in f32."""
    b, s, d = u.shape
    n = at.shape[0]
    n_chunks = s // chunk
    grid = (b, d // d_block, n_chunks)
    f32 = jnp.float32
    in_specs = [
        pl.BlockSpec((1, chunk, d_block), lambda ib, id_, ic: (ib, ic, id_)),
        pl.BlockSpec((1, chunk, d_block), lambda ib, id_, ic: (ib, ic, id_)),
        pl.BlockSpec((1, chunk, n), lambda ib, id_, ic: (ib, ic, 0)),
        pl.BlockSpec((1, chunk, n), lambda ib, id_, ic: (ib, ic, 0)),
        pl.BlockSpec((n, d_block), lambda ib, id_, ic: (0, id_)),
    ]
    y_spec = pl.BlockSpec((1, chunk, d_block),
                          lambda ib, id_, ic: (ib, ic, id_))
    scratch = [pltpu.VMEM((n, d_block), f32)]
    kernel = functools.partial(_scan_kernel, chunk=chunk,
                               with_states=with_states)
    args = (u.astype(f32), delta.astype(f32), B.astype(f32), C.astype(f32),
            at)
    if not with_states:
        return pl.pallas_call(
            kernel, grid=grid, in_specs=in_specs, out_specs=y_spec,
            out_shape=jax.ShapeDtypeStruct((b, s, d), f32),
            scratch_shapes=scratch, interpret=_interpret(),
        )(*args)
    h0_spec = pl.BlockSpec((1, 1, n, d_block),
                           lambda ib, id_, ic: (ib, ic, 0, id_))
    return pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=(y_spec, h0_spec),
        out_shape=(
            jax.ShapeDtypeStruct((b, s, d), f32),
            jax.ShapeDtypeStruct((b, n_chunks, n, d), f32),
        ),
        scratch_shapes=scratch, interpret=_interpret(),
    )(*args)


def _scan_bwd_kernel(u_ref, delta_ref, b_ref, c_ref, at_ref, h0_ref, g_ref,
                     du_ref, ddelta_ref, db_ref, dc_ref, dat_ref,
                     gh_scratch, hs_scratch, dat_scratch, *, chunk,
                     n_chunks):
    """One reverse-ordered chunk of the cotangent recurrence.

    gh ("grad of h") carries dL/dh_t across the chunk boundary in VMEM
    scratch; hs_scratch holds the chunk's recomputed states (the only
    place full per-step states ever exist — VMEM, [chunk, n, d_block]).
    """
    ic = pl.program_id(2)  # 0 = LAST chunk (reverse iteration)

    @pl.when(ic == 0)
    def _reset():
        gh_scratch[:] = jnp.zeros_like(gh_scratch)
        dat_scratch[:] = jnp.zeros_like(dat_scratch)

    at = at_ref[...]      # [n, d]
    h0 = h0_ref[0, 0]     # [n, d] state entering this chunk

    # ---- pass 1: recompute post-step states h_t for t in [0, chunk) ----
    def fwd_body(t, h):
        dt = delta_ref[0, t][None, :]
        da = jnp.exp(dt * at)
        dbu = (dt * u_ref[0, t][None, :]) * b_ref[0, t][:, None]
        h = da * h + dbu
        hs_scratch[t] = h
        return h

    jax.lax.fori_loop(0, chunk, fwd_body, h0)

    # ---- pass 2: reverse cotangent recurrence ----
    def bwd_body(rt, gh):
        t = chunk - 1 - rt
        g = g_ref[0, t][None, :]               # [1, d]
        dt = delta_ref[0, t][None, :]          # [1, d]
        bt = b_ref[0, t][:, None]              # [n, 1]
        ct = c_ref[0, t][:, None]              # [n, 1]
        ut = u_ref[0, t][None, :]              # [1, d]
        h_t = hs_scratch[t]                    # [n, d]
        h_prev = jnp.where(t == 0, h0, hs_scratch[jnp.maximum(t - 1, 0)])
        da = jnp.exp(dt * at)                  # [n, d]

        # dC_t[n] = Σ_d h_t·g
        dc_ref[0, 0, t] = jnp.sum(h_t * g, axis=1)
        gh = gh + ct * g                       # dL/dh_t, full

        # dbu branch: dbu = (δ·u) ⊗ B
        ghb = gh * bt                          # [n, d]
        sum_ghb = jnp.sum(ghb, axis=0)[None, :]  # [1, d]
        du_ref[0, t] = (dt * sum_ghb)[0].astype(du_ref.dtype)
        ddelta_dbu = ut * sum_ghb              # [1, d]
        db_ref[0, 0, t] = jnp.sum(gh * (dt * ut), axis=1)

        # da branch: da = exp(δ ⊗ at), applied to h_prev
        ghh = gh * h_prev * da                 # [n, d]
        ddelta_da = jnp.sum(ghh * at, axis=0)[None, :]
        ddelta_ref[0, t] = (ddelta_dbu + ddelta_da)[0].astype(
            ddelta_ref.dtype)
        dat_scratch[:] += ghh * dt

        # propagate to t-1
        return da * gh

    gh_scratch[:] = jax.lax.fori_loop(0, chunk, bwd_body, gh_scratch[...])

    @pl.when(ic == n_chunks - 1)  # first chunk (reverse order) → flush dA
    def _fin():
        dat_ref[0] = dat_scratch[...]


def _scan_bwd_pallas(u, delta, B, C, at, h0s, g, chunk, d_block):
    b, s, d = u.shape
    n = at.shape[0]
    n_chunks = s // chunk
    nd = d // d_block
    f32 = jnp.float32
    grid = (b, nd, n_chunks)

    def rev(ic):
        return n_chunks - 1 - ic

    in_specs = [
        pl.BlockSpec((1, chunk, d_block),
                     lambda ib, id_, ic: (ib, rev(ic), id_)),   # u
        pl.BlockSpec((1, chunk, d_block),
                     lambda ib, id_, ic: (ib, rev(ic), id_)),   # delta
        pl.BlockSpec((1, chunk, n),
                     lambda ib, id_, ic: (ib, rev(ic), 0)),     # B
        pl.BlockSpec((1, chunk, n),
                     lambda ib, id_, ic: (ib, rev(ic), 0)),     # C
        pl.BlockSpec((n, d_block), lambda ib, id_, ic: (0, id_)),  # at
        pl.BlockSpec((1, 1, n, d_block),
                     lambda ib, id_, ic: (ib, rev(ic), 0, id_)),  # h0s
        pl.BlockSpec((1, chunk, d_block),
                     lambda ib, id_, ic: (ib, rev(ic), id_)),   # g
    ]
    out_specs = (
        pl.BlockSpec((1, chunk, d_block),
                     lambda ib, id_, ic: (ib, rev(ic), id_)),   # du
        pl.BlockSpec((1, chunk, d_block),
                     lambda ib, id_, ic: (ib, rev(ic), id_)),   # ddelta
        # dB/dC get a leading d-block axis (summed by the caller —
        # different d-blocks each contribute)
        pl.BlockSpec((1, 1, chunk, n),
                     lambda ib, id_, ic: (id_, ib, rev(ic), 0)),  # db
        pl.BlockSpec((1, 1, chunk, n),
                     lambda ib, id_, ic: (id_, ib, rev(ic), 0)),  # dc
        # dat: per-batch accumulator flushed on the last (reverse) chunk;
        # caller sums over batch
        pl.BlockSpec((1, n, d_block), lambda ib, id_, ic: (ib, 0, id_)),
    )
    out_shape = (
        jax.ShapeDtypeStruct((b, s, d), f32),
        jax.ShapeDtypeStruct((b, s, d), f32),
        jax.ShapeDtypeStruct((nd, b, s, n), f32),
        jax.ShapeDtypeStruct((nd, b, s, n), f32),
        jax.ShapeDtypeStruct((b, n, d), f32),
    )
    du, ddelta, db, dc, dat = pl.pallas_call(
        functools.partial(_scan_bwd_kernel, chunk=chunk, n_chunks=n_chunks),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((n, d_block), f32),          # gh carry
            pltpu.VMEM((chunk, n, d_block), f32),   # recomputed states
            pltpu.VMEM((n, d_block), f32),          # dat accumulator
        ],
        interpret=_interpret(),
    )(u.astype(f32), delta.astype(f32), B.astype(f32), C.astype(f32),
      at, h0s, g.astype(f32))
    return du, ddelta, db.sum(0), dc.sum(0), dat.sum(0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def _chunked_scan(u, delta, A, B, C, D, chunk, d_block):
    f32 = jnp.float32
    at = A.T.astype(f32)
    y = _scan_fwd_pallas(u, delta, B, C, at, chunk, d_block,
                         with_states=False)
    return y + u.astype(f32) * D[None, None].astype(f32)


def _chunked_fwd(u, delta, A, B, C, D, chunk, d_block):
    f32 = jnp.float32
    at = A.T.astype(f32)
    y, h0s = _scan_fwd_pallas(u, delta, B, C, at, chunk, d_block,
                              with_states=True)
    out = y + u.astype(f32) * D[None, None].astype(f32)
    return out, (u, delta, A, B, C, D, h0s)


def _chunked_bwd(chunk, d_block, res, g):
    u, delta, A, B, C, D, h0s = res
    f32 = jnp.float32
    at = A.T.astype(f32)
    du, ddelta, db, dc, dat = _scan_bwd_pallas(
        u, delta, B, C, at, h0s, g, chunk, d_block)
    # D-skip terms (outside the kernel: pure elementwise)
    g32 = g.astype(f32)
    du = du + g32 * D[None, None].astype(f32)
    dD = jnp.sum(g32 * u.astype(f32), axis=(0, 1))
    dA = dat.T  # at = A.T
    return (du.astype(u.dtype), ddelta.astype(delta.dtype),
            dA.astype(A.dtype), db.astype(B.dtype), dc.astype(C.dtype),
            dD.astype(D.dtype))


_chunked_scan.defvjp(_chunked_fwd, _chunked_bwd)


@functools.partial(jax.jit, static_argnames=("chunk", "d_block"))
def chunked_selective_scan(u, delta, A, B, C, D, *, chunk=128,
                           d_block=None):
    """y[b,s,d] for h_t = exp(Δ_t A)·h_{t-1} + Δ_t u_t B_t, y_t = C_t·h_t
    (+ u·D skip). Shapes as ``associative_selective_scan``. Training-safe:
    the custom VJP is recompute-based and never materializes [b,s,d,n]
    (backward VMEM: chunk·n·d_block states per grid cell)."""
    b, s, d = u.shape
    n = A.shape[1]
    if d_block is None:
        d_block = d if d <= 512 else 256
        # keep the backward's recomputed-state scratch within VMEM budget
        while chunk * n * d_block * 4 > 8 * 1024 * 1024 and d_block > 128:
            d_block //= 2
    if s % chunk:
        raise ValueError(f"seq len {s} not divisible by chunk {chunk}")
    if d % d_block:
        raise ValueError(f"d {d} not divisible by d_block {d_block}")
    return _chunked_scan(u, delta, A, B, C, D, chunk, d_block)
