"""Pallas TPU kernels and their XLA reference fallbacks.

Parity target: paddle/phi/kernels/fusion/ (flash_attn, fused_rope,
rms_norm, masked_multihead_attention, moe dispatch) — here implemented as
Pallas kernels where XLA fusion is insufficient, with pure-XLA fallbacks
that are numerically the source of truth.
"""
