"""Pallas TPU kernels and their XLA reference fallbacks.

Parity target: paddle/phi/kernels/fusion/ (flash_attn, fused_rope,
rms_norm, fused_groupnorm, masked_multihead_attention, moe dispatch) —
here implemented as Pallas kernels where XLA fusion is insufficient,
with pure-XLA fallbacks that are numerically the source of truth.

Modules: flash_attention (fwd + fused 1-pass bwd), pallas_attention,
ring_attention, paged_attention (block-table decode + fused
single-pass decode: in-kernel RoPE + KV-append + attention),
decode_attention (the contiguous-cache fused variant + dispatch gate +
lax references), group_norm (fused NHWC GroupNorm+SiLU, custom VJP),
selective_scan, quant_matmul, rope, ulysses.
"""
