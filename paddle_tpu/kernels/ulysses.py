"""Ulysses-style (SEP) attention — all-to-all sequence↔heads exchange.

Parity: the "sep" axis of Fleet's HybridCommunicateGroup
(DeepSpeed-Ulysses-style segment parallelism, SURVEY.md §2.2): outside
attention the *sequence* dim is sharded across sep ranks; around
attention an all-to-all re-shards to *head* partitioning so every rank
sees full sequences for its head subset.

TPU-native: the exchange is purely declarative — a sharding constraint
moving the sharded dim from seq to heads; GSPMD emits the all-to-all
(one per direction), which is exactly the manual global_scatter/gather
pair the reference would issue.
"""

from __future__ import annotations

from typing import Optional

from ..distributed.sharding import current_mesh, shard_activation


def _head_entry(n_heads: int, mesh):
    """Spec entry for the heads dim inside the attention region: fold sep
    (and tp) onto heads when divisible."""
    tp = mesh.shape.get("tp", 1)
    sep = mesh.shape.get("sep", 1)
    axes = []
    if tp > 1 and n_heads % tp == 0:
        axes.append("tp")
    if sep > 1 and n_heads % (tp * sep) == 0:
        axes.append("sep")
    if not axes:
        return "tp"
    return tuple(axes) if len(axes) > 1 else axes[0]


def ulysses_attention(q, k, v, causal: bool = True, scale=None,
                      training: bool = True, use_flash: bool = True):
    """[batch, seq, heads, dim] attention with SEP all-to-all around it.

    use_flash=False forces the XLA reference attention (numerics
    debugging parity with cfg.use_flash_attention).
    """
    from .flash_attention import _reference_attention, flash_attention

    def attend(q, k, v):
        if use_flash:
            return flash_attention(q, k, v, causal=causal, scale=scale,
                                   training=training)
        return _reference_attention(q, k, v, causal=causal, scale=scale)

    mesh = current_mesh()
    if mesh is None or mesh.shape.get("sep", 1) == 1:
        return attend(q, k, v)
    q_entry = _head_entry(q.shape[2], mesh)
    kv_entry = _head_entry(k.shape[2], mesh)
    # seq gathered, heads scattered (the all-to-all happens here)
    q = shard_activation(q, ("dp", "fsdp"), None, q_entry, None)
    k = shard_activation(k, ("dp", "fsdp"), None, kv_entry, None)
    v = shard_activation(v, ("dp", "fsdp"), None, kv_entry, None)
    out = attend(q, k, v)
    # back to sequence sharding for the MLP/TP region
    return shard_activation(out, ("dp", "fsdp"), "sep", "tp", None)
