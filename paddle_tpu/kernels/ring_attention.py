"""Ring attention — context parallelism over the sequence dim.

Parity: PaddleNLP's RingFlashAttention (context_parallel_degree): KV
blocks rotate around the ring of sequence-parallel ranks via p2p while
queries stay resident, with online-softmax merging of per-block results
(SURVEY.md §5 "Long-context").

TPU-native: the ring is a ``shard_map`` over the "sep" axis with
``jax.lax.ppermute`` KV rotation — which XLA lowers to collective-permute
over ICI, overlapped with the per-block attention compute. Per-block
attention + the (m, l, acc) merge are the same online-softmax algebra as
the Pallas flash kernel; block results are merged with logsumexp
renormalization. Causal load-balancing: block (src > my) contributes
nothing and is skipped via masking, src == my is locally causal, src < my
is unmasked. Backward is jax autodiff through the scan+ppermute (the
reverse ring). A fully fused Pallas ring kernel (RDMA inside the kernel,
pallas_guide.md "Ring Collectives") is the planned upgrade; this
formulation is already communication-optimal in volume.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, scale, is_diag):
    """Attention of local q against one rotating kv block, returning
    (numerator [.., d], running max m, denom l) pieces in fp32.

    ``is_diag`` is a traced bool: on the diagonal block the local causal
    mask applies (one score einsum either way — the mask is selected, not
    the computation). q: [b, sq, h, d]; k,v: [b, sk, h, d].
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    qi = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    ki = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    causal_ok = (qi >= ki)[None, None]
    keep = jnp.logical_or(jnp.logical_not(is_diag), causal_ok)
    s = jnp.where(keep, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # [b,h,q,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
    return o, m, l


def ring_attention(
    q, k, v,
    mesh: Optional[Mesh] = None,
    axis: str = "sep",
    causal: bool = True,
    scale: Optional[float] = None,
):
    """q,k,v: [batch, seq, heads, head_dim] — global shapes with the seq
    dim sharded over ``axis``. Returns attention output with the same
    sharding. Chunks are assigned in ring order (rank i holds contiguous
    chunk i), so causal masking is by chunk index."""
    from ..distributed.sharding import current_mesh

    mesh = mesh or current_mesh()
    if mesh is None or mesh.shape.get(axis, 1) == 1:
        from .flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, scale=scale)

    d = q.shape[-1]
    scale_ = scale if scale is not None else d ** -0.5
    n = mesh.shape[axis]
    if k.shape[2] != q.shape[2]:  # GQA: repeat kv heads
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    def local(qc, kc, vc):
        my = jax.lax.axis_index(axis)

        def step(carry, i):
            k_blk, v_blk, m, l, acc = carry
            src = (my - i) % n  # whose chunk we currently hold
            if causal:
                is_diag = src == my
                o_b, m_b, l_b = _block_attn(qc, k_blk, v_blk, scale_, is_diag)
                # skip blocks from the future
                use = src <= my
                m_b = jnp.where(use, m_b, NEG_INF)
                l_b = jnp.where(use, l_b, 0.0)
                o_b = jnp.where(use, o_b, 0.0)
            else:
                o_b, m_b, l_b = _block_attn(
                    qc, k_blk, v_blk, scale_, jnp.bool_(False)
                )
            # online-softmax merge
            m_new = jnp.maximum(m, m_b)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(m_b - m_new)
            l_new = l * alpha + l_b * beta
            acc_new = acc * alpha + o_b * beta
            # rotate kv to the next rank (ring)
            perm = [(r, (r + 1) % n) for r in range(n)]
            k_nxt = jax.lax.ppermute(k_blk, axis, perm)
            v_nxt = jax.lax.ppermute(v_blk, axis, perm)
            return (k_nxt, v_nxt, m_new, l_new, acc_new), None

        b, sq, h, _ = qc.shape
        vary = lambda x: jax.lax.pcast(x, axis, to="varying")  # noqa: E731
        m0 = vary(jnp.full((b, h, sq, 1), NEG_INF, jnp.float32))
        l0 = vary(jnp.zeros((b, h, sq, 1), jnp.float32))
        acc0 = vary(jnp.zeros((b, h, sq, d), jnp.float32))
        (k_f, v_f, m, l, acc), _ = jax.lax.scan(
            step, (kc, vc, m0, l0, acc0), jnp.arange(n)
        )
        l = jnp.where(l == 0.0, 1.0, l)
        out = (acc / l).astype(qc.dtype)  # [b,h,q,d]
        return jnp.transpose(out, (0, 2, 1, 3))

    spec = P(None, axis, None, None)
    fn = shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, axis_names={axis},
    )
    return fn(q, k, v)
