"""Ring attention — context parallelism over the sequence dim.

Parity: PaddleNLP's RingFlashAttention (context_parallel_degree): KV
blocks rotate around the ring of sequence-parallel ranks via p2p while
queries stay resident, with online-softmax merging of per-block results
(SURVEY.md §5 "Long-context"), including its causal load-balanced
variant.

TPU-native: the ring is a ``shard_map`` over the "sep" axis with
``jax.lax.ppermute`` KV rotation — XLA lowers it to collective-permute
over ICI, overlapped with the per-block attention compute. Per-block
attention is the Pallas flash kernel (``mha_with_lse``) when shapes are
MXU-aligned (dense fallback otherwise) and block results merge by
logsumexp renormalization.

Causal load balancing (zigzag): the sequence is viewed as 2n half-chunks
and rank r owns half-chunks (r, 2n-1-r) — the canonical zigzag
assignment. Every ring step then costs every rank exactly two FULL
L×L block attentions (no computed-then-masked blocks), and the local
step is one causal flash call — per-rank FLOPs ≈ half of the naive
compute-everything-mask-later ring under causal. The zigzag
redistribution happens inside this function with two collective permutes
each way, so callers keep ordinary contiguous GSPMD sharding.

Backward is jax autodiff through the scan + ppermute (the reverse ring),
with the flash kernel's custom VJP per block (dlse folded into delta).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
from ..jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _use_flash(sq, sk, d) -> bool:
    aligned = sq % 128 == 0 and sk % 128 == 0 and d % 128 == 0
    if os.environ.get("PADDLE_TPU_FORCE_PALLAS"):
        return aligned
    return aligned and jax.default_backend() == "tpu"


def _attn_lse(q, k, v, causal, scale):
    """(o [b,s,h,d], lse [b,h,s]) block attention; flash when aligned."""
    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if _use_flash(sq, sk, d):
        from .pallas_attention import mha_with_lse

        return mha_with_lse(q, k, v, causal=causal, sm_scale=scale,
                            q_block=min(256, sq), k_block=min(256, sk))
    if h != hk:
        rep = h // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qi = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((qi >= ki)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bqhd", (p / l).astype(v.dtype), v)
    lse = (m + jnp.log(l))[..., 0]  # [b,h,sq]
    return o.astype(q.dtype), lse


def _merge(o_a, lse_a, o_b, lse_b):
    """logsumexp-renormalized merge of two normalized partials."""
    lse_new = jnp.logaddexp(lse_a, lse_b)
    wa = jnp.exp(lse_a - lse_new)  # [b,h,s]
    wb = jnp.exp(lse_b - lse_new)
    o_new = (o_a * wa.transpose(0, 2, 1)[..., None]
             + o_b * wb.transpose(0, 2, 1)[..., None])
    return o_new, lse_new


def ring_attention(
    q, k, v,
    mesh: Optional[Mesh] = None,
    axis: str = "sep",
    causal: bool = True,
    scale: Optional[float] = None,
):
    """q,k,v: [batch, seq, heads, head_dim] — global shapes with the seq
    dim sharded contiguously over ``axis``. Returns attention output with
    the same sharding."""
    from ..distributed.sharding import current_mesh

    mesh = mesh or current_mesh()
    if mesh is None or mesh.shape.get(axis, 1) == 1:
        from .flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, scale=scale)

    d = q.shape[-1]
    scale_ = scale if scale is not None else d ** -0.5
    n = mesh.shape[axis]

    if not causal:
        local = _plain_local
    elif (q.shape[1] // n) % 2 == 0:
        local = _zigzag_local
    else:
        # odd local chunk: zigzag halves don't split evenly — use the
        # contiguous masked ring (correct, but without load balancing)
        local = _causal_contiguous_local
    spec = P(None, axis, None, None)
    fn = shard_map(
        lambda qc, kc, vc: local(qc, kc, vc, axis=axis, n=n, scale=scale_),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names={axis}, check_vma=False,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# non-causal: plain contiguous ring (every block is full work anyway)
# ---------------------------------------------------------------------------
def _plain_local(qc, kc, vc, *, axis, n, scale):
    o0, lse0 = _attn_lse(qc, kc, vc, False, scale)

    def step(carry, _):
        k_blk, v_blk, o, lse = carry
        perm = [(s, (s + 1) % n) for s in range(n)]
        k_nxt = jax.lax.ppermute(k_blk, axis, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis, perm)
        o_b, lse_b = _attn_lse(qc, k_nxt, v_nxt, False, scale)
        o, lse = _merge(o, lse, o_b, lse_b)
        return (k_nxt, v_nxt, o, lse), None

    (k_f, v_f, o, lse), _ = jax.lax.scan(
        step, (kc, vc, o0, lse0), None, length=n - 1
    )
    return o.astype(qc.dtype)


# ---------------------------------------------------------------------------
# causal, odd local chunks: contiguous ring with masked blocks
# ---------------------------------------------------------------------------
def _causal_contiguous_local(qc, kc, vc, *, axis, n, scale):
    b, sl, h, dd = qc.shape
    hk = kc.shape[2]
    my = jax.lax.axis_index(axis)

    def block(q, k, v, is_diag):
        """Dense block attention with a traced diagonal flag."""
        if h != hk:
            rep = h // hk
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        qi = jax.lax.broadcasted_iota(jnp.int32, (sl, sl), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (sl, sl), 1)
        keep = jnp.logical_or(jnp.logical_not(is_diag),
                              (qi >= ki)[None, None])
        s = jnp.where(keep, s, NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o = jnp.einsum("bhqk,bkhd->bqhd", (p / l_safe).astype(v.dtype), v)
        return o.astype(q.dtype), (m + jnp.log(l_safe))[..., 0]

    o0, lse0 = block(qc, kc, vc, jnp.bool_(True))

    def stepi(carry, i):
        k_blk, v_blk, o, lse = carry
        perm = [(s_, (s_ + 1) % n) for s_ in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        src = (my - i) % n
        o_b, lse_b = block(qc, k_blk, v_blk, jnp.bool_(False))
        # blocks from the future contribute nothing
        use = src < my
        lse_b = jnp.where(use, lse_b, NEG_INF)
        o_m, lse_m = _merge(o, lse, o_b, lse_b)
        return (k_blk, v_blk, o_m, lse_m), None

    (k_f, v_f, o, lse), _ = jax.lax.scan(
        stepi, (kc, vc, o0, lse0), jnp.arange(1, n)
    )
    return o.astype(qc.dtype)


# ---------------------------------------------------------------------------
# causal: zigzag load-balanced ring
# ---------------------------------------------------------------------------
def _chunk_owner(c, n):
    """Zigzag owner rank of global half-chunk c (of 2n)."""
    return c if c < n else 2 * n - 1 - c


def _zigzag_local(qc, kc, vc, *, axis, n, scale):
    b, sl, h, dd = qc.shape
    L = sl // 2
    r = jax.lax.axis_index(axis)

    # --- redistribute contiguous -> zigzag -------------------------------
    # rank s holds global half-chunks (2s, 2s+1); zigzag wants (r, 2n-1-r)
    perm_even = [(s, _chunk_owner(2 * s, n)) for s in range(n)]
    perm_odd = [(s, _chunk_owner(2 * s + 1, n)) for s in range(n)]

    def to_zigzag(x):
        a_even = jax.lax.ppermute(x[:, :L], axis, perm_even)
        a_odd = jax.lax.ppermute(x[:, L:], axis, perm_odd)
        # this rank's chunks are {r, 2n-1-r}: exactly one is even
        r_even = (r % 2 == 0)
        slot0 = jnp.where(r_even, a_even, a_odd)  # chunk r
        slot1 = jnp.where(r_even, a_odd, a_even)  # chunk 2n-1-r
        return slot0, slot1

    q0, q1 = to_zigzag(qc)
    k0, k1 = to_zigzag(kc)
    v0, v1 = to_zigzag(vc)

    # --- step 0: local causal attention over [chunk r ; chunk 2n-1-r] ---
    # concat order == global order (r < 2n-1-r), so plain causal applies
    o_loc, lse_loc = _attn_lse(
        jnp.concatenate([q0, q1], axis=1),
        jnp.concatenate([k0, k1], axis=1),
        jnp.concatenate([v0, v1], axis=1),
        True, scale,
    )
    acc0_o, acc0_l = o_loc[:, :L], lse_loc[:, :, :L]
    acc1_o, acc1_l = o_loc[:, L:], lse_loc[:, :, L:]

    # --- ring steps: two FULL LxL attentions per step, no masked work ---
    # scan with explicit step index to know src = (r - i) % n
    def stepi(carry, i):
        k0c, k1c, v0c, v1c, a0o, a0l, a1o, a1l = carry
        perm = [(s, (s + 1) % n) for s in range(n)]
        k0c = jax.lax.ppermute(k0c, axis, perm)
        k1c = jax.lax.ppermute(k1c, axis, perm)
        v0c = jax.lax.ppermute(v0c, axis, perm)
        v1c = jax.lax.ppermute(v1c, axis, perm)
        src = (r - i) % n  # rank whose zigzag pair we now hold
        f = src < r  # True: kv pair is from the "past" side for chunk r

        # call 1: q = (f ? chunk r : chunk 2n-1-r) x kv chunk src (full)
        q_sel = jnp.where(f, q0, q1)
        o1, l1 = _attn_lse(q_sel, k0c, v0c, False, scale)
        # call 2: q = chunk 2n-1-r x (f ? kv chunk src : kv chunk
        # 2n-1-src) (full)
        k_sel = jnp.where(f, k0c, k1c)
        v_sel = jnp.where(f, v0c, v1c)
        o2, l2 = _attn_lse(q1, k_sel, v_sel, False, scale)

        m0o, m0l = _merge(a0o, a0l, o1, l1)
        a0o = jnp.where(f, m0o, a0o)
        a0l = jnp.where(f, m0l, a0l)
        t1o, t1l = _merge(a1o, a1l, o2, l2)
        e1o, e1l = _merge(t1o, t1l, o1, l1)
        a1o = jnp.where(f, t1o, e1o)
        a1l = jnp.where(f, t1l, e1l)
        return (k0c, k1c, v0c, v1c, a0o, a0l, a1o, a1l), None

    (k0, k1, v0, v1, acc0_o, acc0_l, acc1_o, acc1_l), _ = jax.lax.scan(
        stepi,
        (k0, k1, v0, v1, acc0_o, acc0_l, acc1_o, acc1_l),
        jnp.arange(1, n),
    )

    # --- redistribute zigzag -> contiguous ------------------------------
    inv_even = [(d_, s_) for (s_, d_) in perm_even]
    inv_odd = [(d_, s_) for (s_, d_) in perm_odd]
    r_even = (r % 2 == 0)
    even_out = jnp.where(r_even, acc0_o, acc1_o)  # the even chunk we hold
    odd_out = jnp.where(r_even, acc1_o, acc0_o)
    h0 = jax.lax.ppermute(even_out, axis, inv_even)  # chunk 2r
    h1 = jax.lax.ppermute(odd_out, axis, inv_odd)  # chunk 2r+1
    return jnp.concatenate([h0, h1], axis=1).astype(qc.dtype)
