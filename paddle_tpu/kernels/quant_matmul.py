"""Pallas weight-only quantized matmul (parity: phi ``weight_only_linear``,
paddle/phi/kernels/fusion/ weight-only int8/int4 GEMM via CUTLASS).

TPU-native design: the weight stays int8 (or int4 packed two-per-byte) in
HBM and is dequantized *inside the kernel* after the block is DMA'd to
VMEM — so HBM traffic is halved (int8) or quartered (int4) versus bf16.
That bandwidth saving is the entire value of weight-only quantization on
a decode-bound workload; the MXU still computes in bf16/f32, matching the
reference's approach (dequant-to-half + tensor-core GEMM) rather than
true int8 arithmetic.

Group-wise scales: ``scale[g, n]`` covers rows ``[g*group_size, (g+1)*
group_size)`` of the ``[k, n]`` weight. ``k_block`` must be a multiple of
``group_size`` (or group_size >= k_block and divisible) so each kernel
block sees whole groups.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def quantize_weight_int8_grouped(w: jax.Array, group_size: int = 128):
    """Symmetric group-wise int8 along the in (k) axis.

    w: [k, n] → (q int8 [k, n], scale f32 [k // group_size, n]).
    """
    k, n = w.shape
    if k % group_size:
        raise ValueError(f"k={k} not divisible by group_size={group_size}")
    wf = w.astype(jnp.float32).reshape(k // group_size, group_size, n)
    amax = jnp.max(jnp.abs(wf), axis=1, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return q.reshape(k, n), scale[:, 0, :]


def _largest_group(k: int, group_size: int) -> int:
    """Largest divisor of ``k`` that is <= group_size (>= 1) — the
    suggestion the int4 error message offers."""
    g = min(group_size, k)
    while g > 1 and k % g:
        g -= 1
    return g


def quantize_weight_int4_grouped(w: jax.Array, group_size: int = 128):
    """Symmetric group-wise int4, packed two values per int8 byte along k.

    w: [k, n] → (packed int8 [k // 2, n], scale f32 [k // group_size, n]).
    Row 2i lives in the low nibble of packed row i, row 2i+1 in the high
    nibble (the order ``_unpack_int4`` inverts — pinned by test).
    """
    k, n = w.shape
    if k % 2:
        raise ValueError(
            f"int4 packing stores two rows per byte, so the in (k) "
            f"dimension must be even; got k={k}. Pad the weight with "
            f"one zero row (scales are per-group, a zero row is "
            f"exact) or keep this layer at int8.")
    if k % group_size:
        raise ValueError(
            f"k={k} is not divisible by group_size={group_size}: "
            f"group-wise scales cover whole [group_size, n] row "
            f"blocks. Pick a group_size that divides k (e.g. "
            f"group_size={_largest_group(k, group_size)}), or pass "
            f"group_size=k for one degenerate whole-column group — "
            f"WeightOnlyLinear does that fallback automatically.")
    wf = w.astype(jnp.float32).reshape(k // group_size, group_size, n)
    amax = jnp.max(jnp.abs(wf), axis=1, keepdims=True)
    scale = jnp.maximum(amax / 7.0, 1e-8)
    q = jnp.clip(jnp.round(wf / scale), -7, 7).astype(jnp.int8).reshape(k, n)
    lo = q[0::2] & 0xF
    hi = (q[1::2] & 0xF) << 4
    return (lo | hi).astype(jnp.int8), scale[:, 0, :]


def _unpack_int4(packed: jax.Array) -> jax.Array:
    """[k//2, n] packed → [k, n] int32 in [-8, 7] (sign-extended nibbles).

    Mosaic-friendly formulation: no row interleave (stack/reshape of the
    sublane dim doesn't lower) — duplicate each packed row, then select
    the low/high nibble by row parity with a broadcast iota.
    """
    kk, n = packed.shape
    rep = jnp.repeat(packed.astype(jnp.int32), 2, axis=0)  # [k, n]
    parity = jax.lax.broadcasted_iota(jnp.int32, (2 * kk, n), 0) % 2
    nib = (rep >> (parity * 4)) & 0xF
    return (nib ^ 8) - 8


def _dequant_block(wq, scale_blk, group_size, k_block, out_dtype):
    """wq [k_block, n_block] int8 + scale [k_block//group_size, n_block]
    → dequantized [k_block, n_block] in out_dtype."""
    groups = k_block // group_size
    w = wq.astype(jnp.float32).reshape(groups, group_size, -1)
    w = w * scale_blk.astype(jnp.float32)[:, None, :]
    return w.reshape(k_block, -1).astype(out_dtype)


def _kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, group_size, k_block,
            n_k_blocks, is_int4):
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    wq = w_ref[...]
    if is_int4:
        wq = _unpack_int4(wq)
    w = _dequant_block(wq, s_ref[0], group_size, k_block, x_ref.dtype)
    acc_ref[...] += jax.lax.dot_general(
        x_ref[...], w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(kb == n_k_blocks - 1)
    def _done():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("group_size", "weight_dtype", "m_block", "n_block",
                     "k_block"))
def weight_only_matmul_pallas(x, qweight, scale, *, group_size=128,
                              weight_dtype="int8", m_block=256, n_block=256,
                              k_block=256):
    """y = x @ dequant(qweight). x [m, k]; qweight int8 [k, n] (int8) or
    [k//2, n] (int4 packed); scale [k//group_size, n]."""
    is_int4 = weight_dtype == "int4"
    m, k = x.shape
    n = qweight.shape[1]
    if is_int4 and qweight.shape[0] * 2 != k:
        raise ValueError("packed int4 weight must have k/2 rows")
    if not is_int4 and qweight.shape[0] != k:
        raise ValueError("int8 weight must have k rows")
    m_block = min(m_block, m)
    n_block = min(n_block, n)
    k_block = min(k_block, k)
    if m % m_block or n % n_block or k % k_block:
        raise ValueError(
            f"shapes ({m},{k})x({k},{n}) not divisible by blocks "
            f"({m_block},{k_block},{n_block})")
    if k_block % group_size:
        raise ValueError(
            f"k_block={k_block} must be a multiple of group_size={group_size}")
    grid = (m // m_block, n // n_block, k // k_block)
    kern = functools.partial(
        _kernel, group_size=group_size, k_block=k_block,
        n_k_blocks=grid[2], is_int4=is_int4)
    wrows = k_block // 2 if is_int4 else k_block
    # scale goes in as [n_k_blocks, groups_per_k_block, n]: Mosaic needs
    # the last-two block dims divisible by (8, 128) OR equal to the full
    # array dims; groups_per_k_block is tiny, so make it a full dim.
    gpb = k_block // group_size
    scale3 = scale.reshape(grid[2], gpb, n)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((m_block, k_block), lambda i, j, kb: (i, kb)),
            pl.BlockSpec((wrows, n_block), lambda i, j, kb: (kb, j)),
            pl.BlockSpec((1, gpb, n_block), lambda i, j, kb: (kb, 0, j)),
        ],
        out_specs=pl.BlockSpec((m_block, n_block), lambda i, j, kb: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((m_block, n_block), jnp.float32)],
        interpret=_interpret(),
    )(x, qweight, scale3)


def weight_only_matmul_xla(x, qweight, scale, *, group_size=128,
                           weight_dtype="int8"):
    """Reference XLA path (also the small-shape fallback): dequantize then
    matmul; XLA fuses the scale multiply into the dot's operand."""
    if weight_dtype == "int4":
        qweight = _unpack_int4(qweight)
    k, n = qweight.shape
    w = qweight.astype(jnp.float32).reshape(k // group_size, group_size, n)
    w = (w * scale.astype(jnp.float32)[:, None, :]).reshape(k, n)
    return jnp.matmul(x, w.astype(x.dtype))
