"""Pallas TPU paged decode-attention kernel.

Parity: phi ``masked_multihead_attention`` / ``fused_multi_transformer``
(paddle/phi/kernels/fusion/ — the reference's single-token decode
attention over per-sequence KV caches), upgraded to a vLLM-style page
pool.

The TPU-native point (VERDICT r1 item 3): the kernel consumes the block
table DIRECTLY via scalar prefetch — the page id becomes the kv block's
index-map coordinate, so each decode step streams exactly the pages a
slot actually uses. No ``[slots, max_ctx]`` gather into HBM, no dense
attention over padding: HBM traffic per step ∝ Σ seq_lens, not
slots × max_len.

Structure:
  - grid = (slots, kv_heads, max_pages) with pages innermost; the online
    softmax running stats live in VMEM scratch across page steps.
  - block table + seq_lens are scalar-prefetched; pages past a slot's
    length are pruned (index map clamps to the last active page — a
    revisited block issues no DMA — and pl.when skips the compute).
  - GQA is native: q is [slots, kv_heads, group, d]; all q heads of a
    group share one kv page stream.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..jax_compat import tpu_compiler_params

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _decode_kernel(bt_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scratch, l_scratch, acc_scratch,
                   *, scale, page_size, max_pages, group_pad):
    s = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    seq_len = lens_ref[s]  # inclusive position of the current token
    last_page = seq_len // page_size

    @pl.when(j <= last_page)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)  # [group_pad, d]
        k = k_ref[...]  # [page_size, d]
        v = v_ref[...]
        sc = jax.lax.dot_general(
            q, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [group_pad, page_size]
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, sc.shape, 1
        )
        sc = jnp.where(pos <= seq_len, sc, NEG_INF)

        m_prev = m_scratch[:, :1]
        l_prev = l_scratch[:, :1]
        m_cur = jnp.max(sc, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(sc - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scratch[:] = acc_scratch[:] * alpha + pv
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(j == max_pages - 1)
    def _fin():
        l = l_scratch[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scratch[:] / l).astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, block_tables, seq_lens,
                           scale=None):
    """q: [slots, kv_heads, group, d] (one decode token per slot).

    k_pages/v_pages: [kv_heads, n_pages, page_size, d] — head-major, the
    TPU-tileable layout: the per-grid-step block is one head's one page,
    so the block's LAST TWO dims are (page_size, d) = full tiled minor
    dims. (A head-minor pool [pages, page_size, kvh, d] cannot lower:
    selecting 1 of kvh in the sublane dim is a strided DMA the Mosaic
    lowering rejects — found the first time a 32-kv-head 7B model hit
    real silicon; small models with kvh==1 never trip it.)
    block_tables: [slots, max_pages] int32; seq_lens: [slots] int32 —
    slot i attends to positions [0, seq_lens[i]] inclusive.
    Returns [slots, kv_heads, group, d].
    """
    slots, kvh, group, d = q.shape
    _, n_pages, page_size, _ = k_pages.shape
    max_pages = block_tables.shape[1]
    if scale is None:
        scale = d ** -0.5

    # pad the q-head group to the fp32 sublane tile (8)
    group_pad = max(8, -(-group // 8) * 8)
    if group_pad != group:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, group_pad - group), (0, 0)))

    def q_index(s, h, j, bt_ref, lens_ref):
        return (s, h, 0, 0)

    def kv_index(s, h, j, bt_ref, lens_ref):
        # clamp to the slot's last active page: pruned steps revisit the
        # previous block, so no DMA is issued for them
        last = lens_ref[s] // page_size
        return (h, bt_ref[s, jnp.minimum(j, last)], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, kvh, max_pages),
        in_specs=[
            pl.BlockSpec((1, 1, group_pad, d), q_index),
            pl.BlockSpec((None, None, page_size, d), kv_index),
            pl.BlockSpec((None, None, page_size, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, group_pad, d), q_index),
        scratch_shapes=[
            pltpu.VMEM((group_pad, 128), jnp.float32),
            pltpu.VMEM((group_pad, 128), jnp.float32),
            pltpu.VMEM((group_pad, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel, scale=scale, page_size=page_size,
        max_pages=max_pages, group_pad=group_pad,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, kvh, group_pad, d), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(jnp.asarray(block_tables, jnp.int32),
      jnp.asarray(seq_lens, jnp.int32), q, k_pages, v_pages)
    return out[:, :, :group, :]
