"""Pallas TPU paged decode-attention kernel.

Parity: phi ``masked_multihead_attention`` / ``fused_multi_transformer``
(paddle/phi/kernels/fusion/ — the reference's single-token decode
attention over per-sequence KV caches), upgraded to a vLLM-style page
pool.

The TPU-native point (VERDICT r1 item 3): the kernel consumes the block
table DIRECTLY via scalar prefetch — the page id becomes the kv block's
index-map coordinate, so each decode step streams exactly the pages a
slot actually uses. No ``[slots, max_ctx]`` gather into HBM, no dense
attention over padding: HBM traffic per step ∝ Σ seq_lens, not
slots × max_len.

Structure:
  - grid = (slots, kv_heads, max_pages) with pages innermost; the online
    softmax running stats live in VMEM scratch across page steps.
  - block table + seq_lens are scalar-prefetched; pages past a slot's
    length are pruned (index map clamps to the last active page — a
    revisited block issues no DMA — and pl.when skips the compute).
  - GQA is native: q is [slots, kv_heads, group, d]; all q heads of a
    group share one kv page stream.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..jax_compat import tpu_compiler_params

NEG_INF = -1e30
# THE int8-KV quantization epsilon (scale = max(absmax/127, eps)) —
# one constant shared by the in-kernel quantize-on-append below and
# the XLA append paths (inference.paged.quantize_kv_rows imports it):
# a divergent eps would silently break the fused-vs-unfused
# bit-identical-pools contract
KV_QUANT_EPS = 1e-8


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _decode_kernel(bt_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scratch, l_scratch, acc_scratch,
                   *, scale, page_size, max_pages, group_pad):
    s = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    seq_len = lens_ref[s]  # inclusive position of the current token
    last_page = seq_len // page_size

    @pl.when(j <= last_page)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)  # [group_pad, d]
        k = k_ref[...]  # [page_size, d]
        v = v_ref[...]
        sc = jax.lax.dot_general(
            q, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [group_pad, page_size]
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, sc.shape, 1
        )
        sc = jnp.where(pos <= seq_len, sc, NEG_INF)

        m_prev = m_scratch[:, :1]
        l_prev = l_scratch[:, :1]
        m_cur = jnp.max(sc, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(sc - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scratch[:] = acc_scratch[:] * alpha + pv
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(j == max_pages - 1)
    def _fin():
        l = l_scratch[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scratch[:] / l).astype(o_ref.dtype)


def paged_decode_attention(q, k_pages, v_pages, block_tables, seq_lens,
                           scale=None):
    """q: [slots, kv_heads, group, d] (one decode token per slot).

    k_pages/v_pages: [kv_heads, n_pages, page_size, d] — head-major, the
    TPU-tileable layout: the per-grid-step block is one head's one page,
    so the block's LAST TWO dims are (page_size, d) = full tiled minor
    dims. (A head-minor pool [pages, page_size, kvh, d] cannot lower:
    selecting 1 of kvh in the sublane dim is a strided DMA the Mosaic
    lowering rejects — found the first time a 32-kv-head 7B model hit
    real silicon; small models with kvh==1 never trip it.)
    block_tables: [slots, max_pages] int32; seq_lens: [slots] int32 —
    slot i attends to positions [0, seq_lens[i]] inclusive.
    Returns [slots, kv_heads, group, d].
    """
    slots, kvh, group, d = q.shape
    _, n_pages, page_size, _ = k_pages.shape
    max_pages = block_tables.shape[1]
    if scale is None:
        scale = d ** -0.5

    # pad the q-head group to the fp32 sublane tile (8)
    group_pad = max(8, -(-group // 8) * 8)
    if group_pad != group:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, group_pad - group), (0, 0)))

    def q_index(s, h, j, bt_ref, lens_ref):
        return (s, h, 0, 0)

    def kv_index(s, h, j, bt_ref, lens_ref):
        # clamp to the slot's last active page: pruned steps revisit the
        # previous block, so no DMA is issued for them
        last = lens_ref[s] // page_size
        return (h, bt_ref[s, jnp.minimum(j, last)], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, kvh, max_pages),
        in_specs=[
            pl.BlockSpec((1, 1, group_pad, d), q_index),
            pl.BlockSpec((None, None, page_size, d), kv_index),
            pl.BlockSpec((None, None, page_size, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, 1, group_pad, d), q_index),
        scratch_shapes=[
            pltpu.VMEM((group_pad, 128), jnp.float32),
            pltpu.VMEM((group_pad, 128), jnp.float32),
            pltpu.VMEM((group_pad, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel, scale=scale, page_size=page_size,
        max_pages=max_pages, group_pad=group_pad,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, kvh, group_pad, d), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(jnp.asarray(block_tables, jnp.int32),
      jnp.asarray(seq_lens, jnp.int32), q, k_pages, v_pages)
    return out[:, :, :group, :]


# ---------------------------------------------------------------------------
# Fused single-pass decode: in-kernel RoPE + KV-append + attention
# ---------------------------------------------------------------------------
def kernel_rope_rot(x, cos, sin):
    """In-kernel half-rotation (Neox/Llama convention, matching
    kernels/rope.apply_rope): x [..., d] f32, cos/sin broadcastable
    [..., d/2]. ONE definition shared by the paged and contiguous fused
    kernels so the convention cannot drift between them."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x2 * cos + x1 * sin], axis=-1)


def kernel_quant_rows(x):
    """In-kernel symmetric per-row int8: x [rows, d] f32 → (int8 rows,
    f32 scales [rows, 1]). ONE definition shared by the paged and
    contiguous fused kernels, matching ``inference.paged.
    quantize_kv_rows`` exactly (absmax/127, round, clip, same eps) so
    the fused quantize-on-append and the XLA scatter paths write
    bit-identical pools."""
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(amax / 127.0, KV_QUANT_EPS)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def online_softmax_update(sc, v, m_prev, l_prev, acc_prev):
    """One streaming-softmax step shared by the fused decode kernels:
    fold scores ``sc`` [q, kblock] and values ``v`` [kblock, d] into the
    running (m, l, acc); returns the updated triple (keepdims stats)."""
    m_cur = jnp.max(sc, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    p = jnp.exp(sc - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    pv = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_prev * alpha + pv


def _fused_decode_kernel(bt_ref, lens_ref, pos_ref, q_ref, kn_ref, vn_ref,
                         k_ref, v_ref, *rest,
                         scale, page_size, max_pages, group_pad, quant):
    if quant:
        (ks_ref, vs_ref, cos_ref, sin_ref, o_ref, ko_ref, vo_ref,
         kso_ref, vso_ref, q_scratch, m_scratch, l_scratch,
         acc_scratch) = rest
    else:
        (cos_ref, sin_ref, o_ref, ko_ref, vo_ref, q_scratch,
         m_scratch, l_scratch, acc_scratch) = rest
    s = pl.program_id(0)
    j = pl.program_id(2)
    seq_len = lens_ref[s]  # position of THIS token (== tokens cached)
    last_page = seq_len // page_size
    offs = seq_len % page_size

    cos = cos_ref[...].astype(jnp.float32)  # [1, d/2] row at pos_ref[s]
    sin = sin_ref[...].astype(jnp.float32)

    def rot(x):
        return kernel_rope_rot(x, cos, sin)

    # rotated new-token K — also the row written back to the pool.
    # The write-back block index is constant over j (the slot's current
    # page + in-page row), so the single row is DMA'd once per (s, h):
    # append traffic is 2 rows/slot/head, not a page rewrite, and the
    # token never round-trips through HBM before attention reads it.
    # Attention merges the CACHE-DTYPE-ROUNDED values (not the f32
    # intermediates): the unfused path attends to the appended row
    # as the pool stores it, and bf16/int8 pools must not flip a greedy
    # argmax between the fused and unfused engines
    k_rot = rot(kn_ref[0, 0].astype(jnp.float32))  # [1, d]
    v_raw = vn_ref[0, 0].astype(jnp.float32)
    if quant:
        # quantize-on-append in-kernel: the int8 row and its f32 scale
        # land together; attention merges the DEQUANTIZED stored values
        kq, kscl = kernel_quant_rows(k_rot)
        vq, vscl = kernel_quant_rows(v_raw)
        ko_ref[...] = kq
        vo_ref[...] = vq
        kso_ref[...] = kscl
        vso_ref[...] = vscl
        k_new = kq.astype(jnp.float32) * kscl
        v_new = vq.astype(jnp.float32) * vscl
    else:
        k_store = k_rot.astype(ko_ref.dtype)
        v_store = v_raw.astype(vo_ref.dtype)
        ko_ref[...] = k_store
        vo_ref[...] = v_store
        k_new = k_store.astype(jnp.float32)
        v_new = v_store.astype(jnp.float32)

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)
        # RoPE q once per (s, h) into scratch (input-ref mutations don't
        # persist across grid steps in interpret mode; scratch does)
        q_scratch[:] = rot(q_ref[0, 0].astype(jnp.float32))

    @pl.when(j <= last_page)
    def _step():
        q = q_scratch[...]  # [group_pad, d] rotated f32
        is_last = j == last_page
        row = jax.lax.broadcasted_iota(jnp.int32, (page_size, 1), 0)
        sel = (row == offs) & is_last
        # merge the new token into the streamed page IN VMEM: the HBM
        # page still holds stale data at `offs`; attention must see the
        # rotated k / raw v of the token being appended this step
        kf = k_ref[...].astype(jnp.float32)
        vf = v_ref[...].astype(jnp.float32)
        if quant:
            # dequantize the streamed page: per-row scales ride as a
            # [page_size, 1] block alongside the [page_size, d] page
            kf = kf * ks_ref[...]
            vf = vf * vs_ref[...]
        k = jnp.where(sel, k_new, kf)
        v = jnp.where(sel, v_new, vf)
        sc = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [group_pad, page_size]
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, sc.shape, 1
        )
        sc = jnp.where(pos <= seq_len, sc, NEG_INF)

        m_new, l_new, acc = online_softmax_update(
            sc, v, m_scratch[:, :1], l_scratch[:, :1], acc_scratch[:])
        acc_scratch[:] = acc
        m_scratch[:] = jnp.broadcast_to(m_new, m_scratch.shape)
        l_scratch[:] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(j == max_pages - 1)
    def _fin():
        l = l_scratch[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scratch[:] / l).astype(o_ref.dtype)


def fused_paged_decode_attention(q, k_new, v_new, k_pages, v_pages,
                                 block_tables, seq_lens, positions,
                                 cos, sin, scale=None,
                                 k_scale=None, v_scale=None):
    """Single-pass decode: RoPE(q, k_new) + append (k_new, v_new) into
    each slot's current page + length-pruned online-softmax attention,
    one kernel per layer.

    q: [slots, kv_heads, group, d] UNROTATED; k_new/v_new:
    [slots, kv_heads, d] the new token's unrotated K / V per slot.
    k_pages/v_pages: [kv_heads, n_pages, page_size, d] head-major pool
    (see ``paged_decode_attention``); ALIASED into the outputs — under
    jit the caller should donate them. seq_lens: [slots] int32, tokens
    already cached (== the new token's in-slot position; slot i attends
    to [0, seq_lens[i]] inclusive of the appended token). positions:
    [slots] int32 RoPE positions (== seq_lens for the serving engine;
    kept separate so callers with custom position_ids stay correct).
    cos/sin: [max_pos, d//2] rope tables — the per-slot row is selected
    by scalar-prefetched position, so rotation costs one table-row read
    instead of a q/k materialization round-trip.

    PRECONDITION (unchecked — indices are traced): seq_lens[i] <
    max_pages * page_size (the slot has a page for the appended row;
    Pallas CLAMPS out-of-range block indices, so violating this
    silently overwrites the last allocated row) and positions[i] <
    cos.shape[0]. The serving engine guarantees both.

    INT8 POOLS: pass ``k_scale``/``v_scale`` f32
    [kv_heads, n_pages, page_size, 1] per-row dequant scales (the
    layout ``inference.paged.init_paged_pool`` builds). The kernel
    quantizes the appended row in-kernel (same absmax rule as the XLA
    append paths), writes payload + scale together, and dequantizes
    each streamed page in VMEM — attention math stays f32. Scale
    blocks mirror the pool blocks with d→1 so they tile wherever the
    pool does.

    Returns (out [slots, kv_heads, group, d], k_pages', v_pages') —
    plus (k_scale', v_scale') when quantized.
    """
    slots, kvh, group, d = q.shape
    _, n_pages, page_size, _ = k_pages.shape
    max_pages = block_tables.shape[1]
    quant = k_scale is not None
    if scale is None:
        scale = d ** -0.5

    group_pad = max(8, -(-group // 8) * 8)
    if group_pad != group:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, group_pad - group), (0, 0)))
    k_new = k_new.reshape(slots, kvh, 1, d)
    v_new = v_new.reshape(slots, kvh, 1, d)
    half = d // 2

    def q_index(s, h, j, bt_ref, lens_ref, pos_ref):
        return (s, h, 0, 0)

    def kv_index(s, h, j, bt_ref, lens_ref, pos_ref):
        last = lens_ref[s] // page_size
        return (h, bt_ref[s, jnp.minimum(j, last)], 0, 0)

    def rope_index(s, h, j, bt_ref, lens_ref, pos_ref):
        return (pos_ref[s], 0)

    def append_index(s, h, j, bt_ref, lens_ref, pos_ref):
        # the new token's row: current page, in-page offset — constant
        # over j, so exactly one row is written back per (s, h)
        return (h, bt_ref[s, lens_ref[s] // page_size],
                lens_ref[s] % page_size, 0)

    in_specs = [
        pl.BlockSpec((1, 1, group_pad, d), q_index),
        pl.BlockSpec((1, 1, 1, d), q_index),
        pl.BlockSpec((1, 1, 1, d), q_index),
        pl.BlockSpec((None, None, page_size, d), kv_index),
        pl.BlockSpec((None, None, page_size, d), kv_index),
    ]
    out_specs = [
        pl.BlockSpec((1, 1, group_pad, d), q_index),
        pl.BlockSpec((None, None, 1, d), append_index),
        pl.BlockSpec((None, None, 1, d), append_index),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((slots, kvh, group_pad, d), q.dtype),
        jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
        jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
    ]
    # operand order: 3 prefetch scalars, q, kn, vn, k_pages(6),
    # v_pages(7), [k_scale(8), v_scale(9),] cos, sin — pools (and
    # scale arrays) alias their outputs so the append is in-place on
    # the donated cache buffers
    aliases = {6: 1, 7: 2}
    operands = [q, k_new, v_new, k_pages, v_pages]
    if quant:
        in_specs += [
            pl.BlockSpec((None, None, page_size, 1), kv_index),
            pl.BlockSpec((None, None, page_size, 1), kv_index),
        ]
        out_specs += [
            pl.BlockSpec((None, None, 1, 1), append_index),
            pl.BlockSpec((None, None, 1, 1), append_index),
        ]
        out_shape += [
            jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
            jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
        ]
        aliases.update({8: 3, 9: 4})
        operands += [k_scale, v_scale]
    in_specs += [
        pl.BlockSpec((1, half), rope_index),
        pl.BlockSpec((1, half), rope_index),
    ]
    operands += [cos, sin]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(slots, kvh, max_pages),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((group_pad, d), jnp.float32),
            pltpu.VMEM((group_pad, 128), jnp.float32),
            pltpu.VMEM((group_pad, 128), jnp.float32),
            pltpu.VMEM((group_pad, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _fused_decode_kernel, scale=scale, page_size=page_size,
        max_pages=max_pages, group_pad=group_pad, quant=quant,
    )
    res = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_interpret(),
    )(jnp.asarray(block_tables, jnp.int32),
      jnp.asarray(seq_lens, jnp.int32),
      jnp.asarray(positions, jnp.int32),
      *operands)
    if quant:
        out, k_pages, v_pages, k_scale, v_scale = res
        return out[:, :, :group, :], k_pages, v_pages, k_scale, v_scale
    out, k_pages, v_pages = res
    return out[:, :, :group, :], k_pages, v_pages
