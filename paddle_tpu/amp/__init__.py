"""AMP (parity: python/paddle/amp/ — auto_cast, decorate, GradScaler).

TPU-native stance: bf16 is the native mixed-precision dtype and needs no
loss scaling; ``GradScaler`` is kept for API parity (and for the rare fp16
path) but degenerates to identity scaling with enable=False or bf16.
``decorate(model, optimizer, level='O2')`` casts floating params to the
compute dtype while the optimizer keeps fp32 masters (multi_precision) —
exactly the reference's O2 master-weight contract
(python/paddle/amp/auto_cast.py, amp_decorate).
"""

from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..core import dtype as dtype_mod

_amp_state = threading.local()


def _stack():
    if not hasattr(_amp_state, "stack"):
        _amp_state.stack = []
    return _amp_state.stack


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """Context marking an AMP region.

    In the reference this flips a C++ AMP dispatch state that inserts casts
    per-op via white/black lists (paddle/fluid/eager/amp_utils.h). In the
    XLA world dtype policy is structural — layers read the active amp state
    at trace time via ``get_amp_dtype()`` and cast activations at region
    entry; matmul-family ops then run in bf16 on the MXU while
    reductions/softmax/norms stay fp32 (our F.* ops already accumulate in
    fp32 unconditionally, which is the white/black-list contract).
    """
    state = {
        "enable": bool(enable),
        "level": level,
        "dtype": dtype_mod.convert_dtype(dtype),
        "white": set(custom_white_list or ()),
        "black": set(custom_black_list or ()),
    }
    _stack().append(state)
    try:
        yield
    finally:
        _stack().pop()


amp_guard = auto_cast


def amp_state():
    s = _stack()
    return s[-1] if s else None


def get_amp_dtype():
    s = amp_state()
    if s and s["enable"]:
        return s["dtype"]
    return None


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """Cast model params to the compute dtype; optimizer keeps fp32 masters.

    Returns (models, optimizers) like paddle.amp.decorate.
    """
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    dt = dtype_mod.convert_dtype(dtype)
    if level == "O2":
        for m in model_list:
            m.to(dt)
    if optimizers is None:
        return models if single_model else model_list
    single_opt = not isinstance(optimizers, (list, tuple))
    opt_list = [optimizers] if single_opt else list(optimizers)
    for o in opt_list:
        o.multi_precision = master_weight is not False
    return (
        models if single_model else model_list,
        optimizers if single_opt else opt_list,
    )


class GradScaler:
    """Dynamic loss scaling (parity: paddle.amp.GradScaler).

    With bf16 (the TPU default) scaling is unnecessary; enable=True with
    fp16 gives the full dynamic-scale state machine, implemented
    functionally so it can live inside the jitted step via
    ``scale_value``/``update_on_grads``.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self.incr_ratio = incr_ratio
        self.decr_ratio = decr_ratio
        self.incr_every_n_steps = incr_every_n_steps
        self.decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self.use_dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * jnp.asarray(self._scale, loss.dtype)

    def unscale_(self, grads):
        if not self._enable:
            return grads
        import jax

        inv = 1.0 / self._scale
        return jax.tree_util.tree_map(lambda g: g * inv, grads)

    def found_inf(self, grads):
        import jax

        leaves = jax.tree_util.tree_leaves(grads)
        bad = jnp.zeros((), jnp.bool_)
        for g in leaves:
            bad = bad | ~jnp.all(jnp.isfinite(g.astype(jnp.float32)))
        return bad

    def update(self, found_inf: bool):
        if not (self._enable and self.use_dynamic):
            return
        if found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self.decr_every_n_nan_or_inf:
                self._scale = max(self._scale * self.decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self.incr_every_n_steps:
                self._scale *= self.incr_ratio
                self._good_steps = 0

    def state_dict(self):
        return {
            "scale": self._scale,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, d):
        self._scale = d["scale"]
        self._good_steps = d["good_steps"]
        self._bad_steps = d["bad_steps"]


def is_bfloat16_supported(device=None):
    """Parity: paddle.amp.is_bfloat16_supported — every TPU generation
    (and XLA:CPU) runs bf16 natively."""
    return True


def is_float16_supported(device=None):
    """Parity: paddle.amp.is_float16_supported. XLA supports f16
    storage/compute on TPU (MXU upconverts); bf16 is the fast path."""
    return True
