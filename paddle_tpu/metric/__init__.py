"""Metrics (parity: python/paddle/metric/ — Metric ABC, Accuracy,
Precision, Recall, Auc)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return type(self).__name__.lower()


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (tuple, list)) else (topk,)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.correct = np.zeros(len(self.topk))
        self.total = 0

    def compute(self, pred, label):
        pred = np.asarray(pred)
        label = np.asarray(label)
        k = max(self.topk)
        top = np.argsort(-pred, axis=-1)[..., :k]
        if label.ndim == pred.ndim:  # one-hot
            label = label.argmax(-1)
        return top == label[..., None]

    def update(self, correct_or_pred, label=None):
        if label is not None:
            corrects = self.compute(correct_or_pred, label)
        else:
            corrects = np.asarray(correct_or_pred)
        n = int(np.prod(corrects.shape[:-1]))
        for i, k in enumerate(self.topk):
            self.correct[i] += corrects[..., :k].any(-1).sum()
        self.total += n
        return self.accumulate()

    def accumulate(self):
        accs = [
            float(c / self.total) if self.total else 0.0 for c in self.correct
        ]
        return accs[0] if len(accs) == 1 else accs

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (np.asarray(preds) > 0.5).astype(int).ravel()
        labels = np.asarray(labels).astype(int).ravel()
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp / denom) if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (np.asarray(preds) > 0.5).astype(int).ravel()
        labels = np.asarray(labels).astype(int).ravel()
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp / denom) if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC-AUC via threshold bucketing (parity: paddle.metric.Auc)."""

    def __init__(self, num_thresholds=4095, name="auc"):
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        self._pos = np.zeros(self.num_thresholds + 1)
        self._neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2:  # [n, 2] probs
            preds = preds[:, 1]
        labels = np.asarray(labels).ravel()
        idx = np.clip(
            (preds.ravel() * self.num_thresholds).astype(int), 0,
            self.num_thresholds,
        )
        np.add.at(self._pos, idx[labels == 1], 1)
        np.add.at(self._neg, idx[labels == 0], 1)

    def accumulate(self):
        tot_pos = self._pos.sum()
        tot_neg = self._neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate TPR over FPR from the highest threshold down
        pos = self._pos[::-1].cumsum()
        neg = self._neg[::-1].cumsum()
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name
