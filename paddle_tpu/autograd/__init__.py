"""Autograd surface (parity: python/paddle/autograd/).

The reference records GradNodes eagerly per op and runs a C++ tape walk on
``loss.backward()`` (paddle/fluid/eager/backward.cc). On TPU reverse-mode
is a program transform: ``jax.grad`` over the functional form of the
model. This module provides the bridge with Paddle-shaped ergonomics:

    loss, grads = backward(model, loss_fn, *inputs)
    optimizer.set_gradients(grads); optimizer.step()

plus ``no_grad`` and a ``PyLayer`` equivalent via jax.custom_vjp.
"""

from __future__ import annotations

import contextlib
from typing import Callable, Dict

import jax

from ..core.functional import extract_params, functional_call
from ..core.module import Layer


def value_and_grad(model: Layer, loss_fn: Callable = None):
    """Build ``f(params, *inputs) -> (loss, grads)``.

    ``loss_fn(output, *extra)`` maps model output to a scalar; if None the
    model's own output must be scalar.
    """

    def fwd(params, *args, rngs=None):
        if loss_fn is None:
            return functional_call(model, params, *args, rngs=rngs)
        out = functional_call(model, params, args[0], rngs=rngs)
        return loss_fn(out, *args[1:])

    return jax.value_and_grad(fwd)


def backward(model: Layer, loss_fn: Callable, *inputs, rngs=None):
    """Eager one-shot: compute loss and grads w.r.t. trainable params.
    Also populates each Parameter's ``.grad`` (parity: loss.backward()
    filling EagerParamBase.grad), which closure-driven optimizers (LBFGS)
    read back."""
    params = extract_params(model, trainable_only=True)
    loss, grads = value_and_grad(model, loss_fn)(params, *inputs, rngs=rngs)
    for p in model.parameters():
        if p.name in grads:
            p.grad = grads[p.name]
    return loss, grads


@contextlib.contextmanager
def no_grad():
    yield


class PyLayer:
    """Custom autograd op (parity: paddle.autograd.PyLayer).

    Subclass with static ``forward(ctx, *args)`` and ``backward(ctx,
    *grads)``; ``apply`` builds a jax.custom_vjp under the hood. ctx is a
    plain namespace whose ``saved`` list is threaded as vjp residuals.
    """

    @classmethod
    def apply(cls, *args):
        import types

        @jax.custom_vjp
        def f(*xs):
            ctx = types.SimpleNamespace(saved=None)
            return cls.forward(ctx, *xs)

        def f_fwd(*xs):
            ctx = types.SimpleNamespace(saved=None)
            out = cls.forward(ctx, *xs)
            return out, ctx.saved

        def f_bwd(saved, g):
            import types as _t

            ctx = _t.SimpleNamespace(saved=saved)
            grads = cls.backward(ctx, g)
            if not isinstance(grads, tuple):
                grads = (grads,)
            return grads

        f.defvjp(f_fwd, f_bwd)
        return f(*args)

    @staticmethod
    def forward(ctx, *args):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

from .functional import hessian, jacobian, jvp, vjp  # noqa: F401,E402
