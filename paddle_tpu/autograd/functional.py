"""Functional autograd transforms (parity: paddle.incubate.autograd /
paddle.autograd — Jacobian, Hessian, jvp, vjp; upstream:
python/paddle/incubate/autograd/functional.py).

On TPU these ARE jax's program transforms — the value added here is the
paddle calling convention (tuple-of-tensors xs, optional cotangents v)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _as_tuple(xs):
    return xs if isinstance(xs, (tuple, list)) else (xs,)


def _maybe_unpack(out, was_single):
    return out[0] if was_single and isinstance(out, (tuple, list)) \
        else out


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """∂func(xs)/∂xs. xs: tensor or tuple. Returns jax-style nested
    jacobian (tuple over inputs when xs is a tuple)."""
    single = not isinstance(xs, (tuple, list))
    xs_t = tuple(jnp.asarray(x) for x in _as_tuple(xs))
    argnums = 0 if single else tuple(range(len(xs_t)))
    return jax.jacobian(lambda *a: func(*a), argnums=argnums)(*xs_t)


def hessian(func, xs, create_graph=False):
    """Hessian of a scalar-valued func."""
    single = not isinstance(xs, (tuple, list))
    xs_t = tuple(jnp.asarray(x) for x in _as_tuple(xs))
    argnums = 0 if single else tuple(range(len(xs_t)))
    return jax.hessian(lambda *a: func(*a), argnums=argnums)(*xs_t)


def vjp(func, xs, v=None):
    """Returns (func(xs), vjp result). ``v``: cotangent(s) matching the
    output structure; defaults to ones (paddle convention)."""
    single = not isinstance(xs, (tuple, list))
    xs_t = tuple(jnp.asarray(x) for x in _as_tuple(xs))
    out, pullback = jax.vjp(lambda *a: func(*a), *xs_t)
    if v is None:
        v = jax.tree_util.tree_map(jnp.ones_like, out)
    grads = pullback(v)
    return out, _maybe_unpack(grads, single)


def jvp(func, xs, v=None):
    """Returns (func(xs), jvp result). ``v``: tangent(s) matching xs;
    defaults to ones."""
    single = not isinstance(xs, (tuple, list))
    xs_t = tuple(jnp.asarray(x) for x in _as_tuple(xs))
    if v is None:
        v_t = tuple(jnp.ones_like(x) for x in xs_t)
    else:
        v_t = tuple(jnp.asarray(t) for t in _as_tuple(v))
    out, tangent = jax.jvp(lambda *a: func(*a), xs_t, v_t)
    return out, tangent
