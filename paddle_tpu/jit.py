"""JIT surface (parity: python/paddle/jit/ — @to_static, jit.save/load).

The reference converts imperative Python to a static Program via AST
rewriting (dy2static) or bytecode tracing (SOT) because its eager and
graph runtimes are different engines. Here tracing-jit IS the engine, so
``to_static`` is ``jax.jit`` over the functional form of the Layer —
including control-flow capture via jax's tracing (the role of SOT's
graph-break machinery is played by jax's own python-control-flow rules).

``jit.save``/``jit.load`` export a compiled, weight-embedded callable via
StableHLO serialization (jax.export) so a saved model runs without the
defining Python code — the deployment contract of
``paddle.jit.save`` → inference program.
"""

from __future__ import annotations

import os
import pickle
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from .core.functional import extract_params, functional_call
from .core.module import Layer


class TracedLayer:
    def __init__(self, layer: Layer, jit_fn, params, input_spec=None):
        self.layer = layer
        self._fn = jit_fn
        self._params = params
        self._input_spec = input_spec

    def _check_spec(self, args, kwargs):
        from .static import InputSpec

        n_spec = len(self._input_spec)
        if len(args) != n_spec:
            raise ValueError(
                f"to_static declared {n_spec} input_spec entries but got "
                f"{len(args)} positional inputs; pass spec'd tensors "
                "positionally (keyword tensors bypass the declared "
                "signature)")
        for i, (spec, arg) in enumerate(zip(self._input_spec, args)):
            if not isinstance(spec, InputSpec):
                continue
            shape = jnp.shape(arg)
            ok = len(shape) == len(spec.shape) and all(
                d is None or d == a for d, a in zip(spec.shape, shape))
            if not ok:
                raise ValueError(
                    f"to_static input {i}: shape {shape} does not match "
                    f"declared {spec}")

    def __call__(self, *args, **kwargs):
        if self._input_spec is not None:
            self._check_spec(args, kwargs)
        return self._fn(self._params, *args, **kwargs)

    @property
    def params(self):
        return self._params


def to_static(layer=None, input_spec=None, full_graph=True, **kw):
    """Decorator/wrapper: returns a jit-compiled callable of the Layer.

    Works as ``@to_static`` on a Layer subclass method-free module or as
    ``to_static(layer)``.
    """

    def wrap(target):
        if isinstance(target, Layer):
            params = extract_params(target)
            fn = jax.jit(
                lambda p, *a, **k: functional_call(target, p, *a, **k)
            )
            return TracedLayer(target, fn, params,
                               input_spec=input_spec)
        # plain function
        return jax.jit(target)

    if layer is None:
        return wrap
    return wrap(layer)


def save(traced, path: str, input_spec: Optional[Sequence] = None):
    """Serialize a compiled forward (StableHLO) + weights.

    ``traced``: a TracedLayer (from to_static) or a Layer (input_spec
    required: a list of jax.ShapeDtypeStruct / arrays).
    """
    if isinstance(traced, Layer):
        traced = to_static(traced)
    if input_spec is None:
        raise ValueError("input_spec required for jit.save")
    from jax import export as jexport

    from .static import InputSpec

    scope = jexport.SymbolicScope()   # ONE scope for every dynamic dim
    # unnamed specs share canonical per-position symbols (d0, d1, ...)
    # so two dynamic-batch inputs are EQUAL-batch, the paddle meaning;
    # give specs distinct name= values to declare independent dims
    specs = [
        x.to_symbolic_struct(
            prefix=(f"{x.name}_" if x.name else "d"), scope=scope)
        if isinstance(x, InputSpec)
        else x if isinstance(x, jax.ShapeDtypeStruct)
        else jax.ShapeDtypeStruct(jnp.shape(x), jnp.asarray(x).dtype)
        for x in input_spec
    ]
    from jax import export as jexport

    def fn(*args):
        return traced._fn(traced._params, *args)

    exported = jexport.export(jax.jit(fn))(*specs)
    payload = {
        "stablehlo": exported.serialize(),
        # symbolic dims are not picklable — record them as None markers
        "in_specs": [
            (tuple(d if isinstance(d, int) else None for d in s.shape),
             str(s.dtype))
            for s in specs
        ],
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(payload, f)


class LoadedFunction:
    def __init__(self, exported):
        self._exported = exported

    def __call__(self, *args):
        out = self._exported.call(*args)
        return out[0] if isinstance(out, (tuple, list)) and len(out) == 1 \
            else out


def load(path: str) -> LoadedFunction:
    from jax import export as jexport

    with open(path + ".pdmodel", "rb") as f:
        payload = pickle.load(f)
    exported = jexport.deserialize(payload["stablehlo"])
    return LoadedFunction(exported)


def not_to_static(fn=None):
    """Parity: paddle.jit.not_to_static — mark a function to be left
    eager by to_static. Tracing here is jax's (no AST rewriting), so the
    marker is metadata only."""
    if fn is None:
        return not_to_static
    fn._paddle_tpu_not_to_static = True
    return fn


def ignore_module(modules):
    """Parity: paddle.jit.ignore_module — modules the dy2static AST
    transformer should skip; jax tracing has no AST pass, so this
    records intent and returns."""
    return None


#: Parity: paddle.jit.TranslatedLayer — the type jit.load returns.
TranslatedLayer = LoadedFunction
