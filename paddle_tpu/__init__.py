"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (the reference, hackerapple/Paddle), re-designed
for JAX/XLA/Pallas/pjit instead of CUDA/Phi/NCCL.

Architecture (see SURVEY.md §7): the reference's kernel registry, IRs,
tensor compiler and collective runtime are *subsumed by XLA*; this package
provides the module/optimizer/tensor API, the hybrid-parallel sharding
engine (DP / ZeRO-1/2/3 / TP / PP / SP / CP / EP expressed as GSPMD
shardings over a jax Mesh), Pallas kernels for the genuinely hot paths,
and the host-side runtime (trainer, data, checkpoint, launch, profiler).
"""

from . import amp  # noqa: F401
from . import audio  # noqa: F401
from . import autograd  # noqa: F401
from . import device  # noqa: F401
from . import distribution  # noqa: F401
from . import errors  # noqa: F401
from . import fft  # noqa: F401
from . import generation  # noqa: F401
from . import flags  # noqa: F401

# PT_FLAGS_default_matmul_precision: process-wide jax matmul precision
# override, applied once at import (first-use time, like the registry's
# xla_* passthrough); empty = jax's own default (bf16 on the MXU)
_mmp = flags.flag("default_matmul_precision")
if _mmp:
    import jax as _jax_cfg

    try:
        _jax_cfg.config.update("jax_default_matmul_precision",
                               str(_mmp))
    except Exception as _e:
        raise ValueError(
            f"PT_FLAGS_default_matmul_precision={_mmp!r} is not a "
            "valid jax matmul precision (use bfloat16|tensorfloat32|"
            "float32|highest, or empty for the default)") from _e
    del _jax_cfg
del _mmp
from . import incubate  # noqa: F401
from . import jit  # noqa: F401
from . import linalg  # noqa: F401
from . import metric  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import signal  # noqa: F401
from . import static  # noqa: F401
from . import utils  # noqa: F401
from .hapi.summary import flops, summary  # noqa: F401
from . import sparse  # noqa: F401
from . import vision  # noqa: F401
from .core import dtype as _dtype_mod
from .core.dtype import (  # noqa: F401
    bfloat16,
    bool_,
    complex64,
    complex128,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .core.functional import functional_call  # noqa: F401
from .core.module import Layer  # noqa: F401
from .core.parameter import Parameter  # noqa: F401
from .core.random import get_rng_state_tracker, seed  # noqa: F401
from .tensor import *  # noqa: F401,F403
from .tensor import to_tensor  # noqa: F401
from .core import tensor_methods as _tensor_methods

# paddle.Tensor METHOD surface onto jax.Array (x.numpy(), x.cast(...),
# x.unsqueeze(...)) — strictly additive, see core/tensor_methods.py
_tensor_methods.install()
from .version import full_version as __version__  # noqa: F401


def save(obj, path):
    from .framework import io

    return io.save(obj, path)


def load(path):
    from .framework import io

    return io.load(path)


def no_grad(fn=None):
    """Parity shim: gradients in this framework are explicit (jax.grad), so
    no_grad is an identity context/decorator kept for API compatibility."""
    import contextlib

    if fn is None:
        return contextlib.nullcontext()
    return fn


def iinfo(dtype):
    import jax.numpy as _jnp
    import numpy as _np

    return _np.iinfo(_jnp.dtype(dtype))


def finfo(dtype):
    import jax.numpy as _jnp
    import numpy as _np

    d = _jnp.dtype(dtype)
    if d == _jnp.bfloat16:
        import ml_dtypes

        return ml_dtypes.finfo(ml_dtypes.bfloat16)
    return _np.finfo(d)


# ---- round-5 migration-surface sweep (top-level paddle names) ----

from . import observability  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import models  # noqa: F401,E402
from .core.parameter import ParamAttr  # noqa: F401,E402
from .device import get_device, set_device  # noqa: F401,E402

import builtins  # noqa: E402
import jax as _jax  # noqa: E402

#: the tensor type IS jax.Array (see tensor.py's module docstring)
Tensor = _jax.Array
bool = bool_  # noqa: A001  (paddle.bool is a public dtype name)


class CPUPlace:
    """Parity: paddle.CPUPlace. Device placement on TPU is owned by
    PJRT/shardings; Places exist so migrating call sites keep working
    (to_tensor(place=...), Config.set_device)."""

    def __repr__(self):
        return "Place(cpu)"

    def __eq__(self, other):
        return type(other) is type(self)

    def __hash__(self):
        return hash(type(self))


class CUDAPlace:
    """Parity: paddle.CUDAPlace(id) — maps to the id-th accelerator."""

    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def __repr__(self):
        return f"Place(accelerator:{self.device_id})"

    def __eq__(self, other):
        return (type(other) is type(self)
                and other.device_id == self.device_id)

    def __hash__(self):
        return hash((type(self), self.device_id))


XPUPlace = CUDAPlace


def grad(outputs, inputs=None, grad_outputs=None, **kw):
    """Parity adapter for paddle.grad. There is no dygraph tape here —
    differentiation is a functional transform — so ``outputs`` must be
    the CALLABLE producing the outputs, and ``inputs`` its example
    arguments: ``paddle_tpu.grad(f, (x, y))`` returns (df/dx, df/dy) at
    (x, y), one gradient per input like paddle.grad. Passing arrays
    raises with the migration hint."""
    if callable(outputs) and inputs is not None:
        args = tuple(inputs) if isinstance(inputs, (list, tuple)) \
            else (inputs,)
        return _jax.grad(outputs,
                         argnums=tuple(range(len(args))))(*args)
    raise TypeError(
        "paddle_tpu.grad has no dygraph tape: pass the function AND its "
        "inputs, e.g. grad(lambda x: loss(x), (x,)) — see "
        "autograd.functional for vjp/jvp/jacobian/hessian")


_grad_enabled = True


class set_grad_enabled:
    """Parity: paddle.set_grad_enabled — context manager tracking the
    flag; gradient computation itself is explicit (jax transforms), so
    the flag only drives is_grad_enabled()."""

    def __init__(self, mode: builtins.bool):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = builtins.bool(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False


def is_grad_enabled():
    return _grad_enabled


class DataParallel(Layer):
    """Parity: paddle.DataParallel(model). On TPU, data parallelism is a
    sharding of the batch axis over the mesh's dp axis inside the one
    compiled program — gradient all-reduce is inserted by GSPMD, so the
    wrapper has no reducer to run. It exists so migrating training
    scripts keep their structure; pass the wrapped model to TrainStep
    with a dp mesh axis for the actual parallelism."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, include_sublayers=True,
                   structured_name_prefix=""):
        # delegate like upstream paddle.DataParallel: checkpoint keys
        # match the UNWRAPPED model, so training with the wrapper and
        # loading into a bare model (the standard infer path) just works
        return self._layers.state_dict(include_sublayers,
                                       structured_name_prefix)

    def set_state_dict(self, state_dict, use_structured_name=True):
        return self._layers.set_state_dict(state_dict,
                                           use_structured_name)

    load_dict = set_state_dict

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self._layers, name)

from .hapi import Model  # noqa: F401,E402
from .hapi import callbacks  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from . import hub  # noqa: F401,E402
