"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (the reference, hackerapple/Paddle), re-designed
for JAX/XLA/Pallas/pjit instead of CUDA/Phi/NCCL.

Architecture (see SURVEY.md §7): the reference's kernel registry, IRs,
tensor compiler and collective runtime are *subsumed by XLA*; this package
provides the module/optimizer/tensor API, the hybrid-parallel sharding
engine (DP / ZeRO-1/2/3 / TP / PP / SP / CP / EP expressed as GSPMD
shardings over a jax Mesh), Pallas kernels for the genuinely hot paths,
and the host-side runtime (trainer, data, checkpoint, launch, profiler).
"""

from . import amp  # noqa: F401
from . import audio  # noqa: F401
from . import autograd  # noqa: F401
from . import device  # noqa: F401
from . import distribution  # noqa: F401
from . import errors  # noqa: F401
from . import fft  # noqa: F401
from . import generation  # noqa: F401
from . import flags  # noqa: F401
from . import incubate  # noqa: F401
from . import jit  # noqa: F401
from . import linalg  # noqa: F401
from . import metric  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import signal  # noqa: F401
from . import static  # noqa: F401
from . import utils  # noqa: F401
from .hapi.summary import flops, summary  # noqa: F401
from . import sparse  # noqa: F401
from . import vision  # noqa: F401
from .core import dtype as _dtype_mod
from .core.dtype import (  # noqa: F401
    bfloat16,
    bool_,
    complex64,
    complex128,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from .core.functional import functional_call  # noqa: F401
from .core.module import Layer  # noqa: F401
from .core.parameter import Parameter  # noqa: F401
from .core.random import get_rng_state_tracker, seed  # noqa: F401
from .tensor import *  # noqa: F401,F403
from .tensor import to_tensor  # noqa: F401
from .version import full_version as __version__  # noqa: F401


def save(obj, path):
    from .framework import io

    return io.save(obj, path)


def load(path):
    from .framework import io

    return io.load(path)


def no_grad(fn=None):
    """Parity shim: gradients in this framework are explicit (jax.grad), so
    no_grad is an identity context/decorator kept for API compatibility."""
    import contextlib

    if fn is None:
        return contextlib.nullcontext()
    return fn


def iinfo(dtype):
    import jax.numpy as _jnp
    import numpy as _np

    return _np.iinfo(_jnp.dtype(dtype))


def finfo(dtype):
    import jax.numpy as _jnp
    import numpy as _np

    d = _jnp.dtype(dtype)
    if d == _jnp.bfloat16:
        import ml_dtypes

        return ml_dtypes.finfo(ml_dtypes.bfloat16)
    return _np.finfo(d)
