"""Loss layers (parity: python/paddle/nn/layer/loss.py)."""

from ...core.module import Layer
from .. import functional as F


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, label_smoothing=0.0, axis=-1):
        super().__init__()
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.label_smoothing = label_smoothing
        self.axis = axis

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(
            input, label,
            soft_label=self.soft_label,
            ignore_index=self.ignore_index,
            reduction=self.reduction,
            axis=self.axis,
            label_smoothing=self.label_smoothing,
        )


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, reduction="mean", ignore_index=-100):
        super().__init__()
        self.reduction = reduction
        self.ignore_index = ignore_index

    def forward(self, input, label):  # noqa: A002
        return F.nll_loss(input, label, self.reduction, self.ignore_index)


class BCEWithLogitsLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.reduction)
