"""Loss layers (parity: python/paddle/nn/layer/loss.py)."""

from ...core.module import Layer
from .. import functional as F


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, label_smoothing=0.0, axis=-1):
        super().__init__()
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.label_smoothing = label_smoothing
        self.axis = axis

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(
            input, label,
            soft_label=self.soft_label,
            ignore_index=self.ignore_index,
            reduction=self.reduction,
            axis=self.axis,
            label_smoothing=self.label_smoothing,
        )


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, reduction="mean", ignore_index=-100):
        super().__init__()
        self.reduction = reduction
        self.ignore_index = ignore_index

    def forward(self, input, label):  # noqa: A002
        return F.nll_loss(input, label, self.reduction, self.ignore_index)


class BCEWithLogitsLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.reduction)


class _PiecewiseL1(Layer):
    """Shared quadratic-below-delta / linear-above-delta loss body.
    ``quad_scale`` multiplies the 0.5·d² zone, ``lin_scale`` the linear
    zone — the only place SmoothL1 and Huber differ."""

    def __init__(self, reduction="mean", delta=1.0):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def _scales(self):
        raise NotImplementedError

    def forward(self, input, label):  # noqa: A002
        import jax.numpy as jnp

        quad_scale, lin_scale = self._scales()
        d = jnp.abs(input - label)
        loss = jnp.where(d < self.delta,
                         quad_scale * 0.5 * d * d,
                         lin_scale * (d - 0.5 * self.delta))
        return _reduce(loss, self.reduction)


class SmoothL1Loss(_PiecewiseL1):
    """Parity: paddle.nn.SmoothL1Loss. Quadratic zone scaled by 1/delta:
    0.5·d²/delta for d<delta, else d−0.5·delta. Coincides with Huber only
    at delta=1 — the two classes are intentionally NOT aliases."""

    def _scales(self):
        return 1.0 / self.delta, 1.0


class HuberLoss(_PiecewiseL1):
    """Classic Huber: 0.5·d² for d<delta, else delta·(d−0.5·delta)."""

    def _scales(self):
        return 1.0, self.delta


class KLDivLoss(Layer):
    """input is LOG-probabilities, label is probabilities (parity)."""

    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        import jax.numpy as jnp

        loss = label * (jnp.log(jnp.clip(label, 1e-30)) - input)
        if self.reduction == "batchmean":
            return jnp.sum(loss) / input.shape[0]
        return _reduce(loss, self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean"):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):  # noqa: A002
        import jax.numpy as jnp

        loss = jnp.maximum(0.0, -label * (input - other) + self.margin)
        return _reduce(loss, self.reduction)


def _reduce(loss, reduction):
    import jax.numpy as jnp

    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss
