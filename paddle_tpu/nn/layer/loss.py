"""Loss layers (parity: python/paddle/nn/layer/loss.py)."""

from ...core.module import Layer
from .. import functional as F


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, label_smoothing=0.0, axis=-1):
        super().__init__()
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.label_smoothing = label_smoothing
        self.axis = axis

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(
            input, label,
            soft_label=self.soft_label,
            ignore_index=self.ignore_index,
            reduction=self.reduction,
            axis=self.axis,
            label_smoothing=self.label_smoothing,
        )


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, reduction="mean", ignore_index=-100):
        super().__init__()
        self.reduction = reduction
        self.ignore_index = ignore_index

    def forward(self, input, label):  # noqa: A002
        return F.nll_loss(input, label, self.reduction, self.ignore_index)


class BCEWithLogitsLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.reduction)


class _PiecewiseL1(Layer):
    """Shared quadratic-below-delta / linear-above-delta loss body.
    ``quad_scale`` multiplies the 0.5·d² zone, ``lin_scale`` the linear
    zone — the only place SmoothL1 and Huber differ."""

    def __init__(self, reduction="mean", delta=1.0):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def _scales(self):
        raise NotImplementedError

    def forward(self, input, label):  # noqa: A002
        import jax.numpy as jnp

        quad_scale, lin_scale = self._scales()
        d = jnp.abs(input - label)
        loss = jnp.where(d < self.delta,
                         quad_scale * 0.5 * d * d,
                         lin_scale * (d - 0.5 * self.delta))
        return _reduce(loss, self.reduction)


class SmoothL1Loss(_PiecewiseL1):
    """Parity: paddle.nn.SmoothL1Loss. Quadratic zone scaled by 1/delta:
    0.5·d²/delta for d<delta, else d−0.5·delta. Coincides with Huber only
    at delta=1 — the two classes are intentionally NOT aliases."""

    def _scales(self):
        return 1.0 / self.delta, 1.0


class HuberLoss(_PiecewiseL1):
    """Classic Huber: 0.5·d² for d<delta, else delta·(d−0.5·delta)."""

    def _scales(self):
        return 1.0, self.delta


class KLDivLoss(Layer):
    """input is LOG-probabilities, label is probabilities (parity)."""

    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.kl_div(input, label, self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean"):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):  # noqa: A002
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


def _reduce(loss, reduction):
    import jax.numpy as jnp

    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


class RNNTLoss(Layer):
    """Parity: paddle.nn.RNNTLoss (warprnnt-backed upstream; here a
    lax.scan + cumlogsumexp lattice DP — see functional.rnnt_loss)."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        from .. import functional as F

        return F.rnnt_loss(
            input, label, input_lengths, label_lengths,
            blank=self.blank, fastemit_lambda=self.fastemit_lambda,
            reduction=self.reduction,
        )


class CTCLoss(Layer):
    """Parity: paddle.nn.CTCLoss (warpctc-backed upstream; here a
    lax.scan log-semiring recursion — see functional.ctc_loss)."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        from .. import functional as F

        return F.ctc_loss(
            log_probs, labels, input_lengths, label_lengths,
            blank=self.blank, reduction=self.reduction,
            norm_by_times=norm_by_times,
        )


class BCELoss(Layer):
    """Parity: paddle.nn.BCELoss (input are probabilities)."""

    def __init__(self, weight=None, reduction="mean"):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        import jax.numpy as jnp

        x = jnp.clip(input, 1e-12, 1.0 - 1e-12)
        loss = -(label * jnp.log(x) + (1.0 - label) * jnp.log1p(-x))
        if self.weight is not None:
            loss = loss * self.weight
        return _reduce(loss, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean"):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label,
                                       self.margin, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean"):
        super().__init__()
        self.margin, self.p, self.epsilon = margin, p, epsilon
        self.swap = swap
        self.reduction = reduction

    def forward(self, input, positive, negative):  # noqa: A002
        return F.triplet_margin_loss(
            input, positive, negative, self.margin, self.p,
            self.epsilon, self.swap, self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.soft_margin_loss(input, label, self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean"):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean"):
        super().__init__()
        self.log_input, self.full, self.epsilon = log_input, full, epsilon
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.poisson_nll_loss(input, label, self.log_input,
                                  self.full, self.epsilon,
                                  self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean"):
        super().__init__()
        self.full, self.epsilon = full, epsilon
        self.reduction = reduction

    def forward(self, input, label, variance):  # noqa: A002
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean"):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)
