"""Recurrent layers (parity: python/paddle/nn/layer/rnn.py — SimpleRNN,
LSTM, GRU with num_layers, bidirectional, time_major).

TPU-native: the time loop is ``jax.lax.scan`` — one compiled recurrence
body whose per-step matmuls batch onto the MXU, instead of the
reference's cuDNN RNN descriptors. The input projection for ALL
timesteps is hoisted out of the scan (one big [b·s, in] @ [in, 4h]
matmul — the same trick cuDNN applies internally), so only the
recurrent h @ U matmul runs per step.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...core import initializer as I
from ...core.module import Layer

__all__ = ["SimpleRNN", "LSTM", "GRU"]


class _RNNBase(Layer):
    GATES = 1  # per-cell gate multiplier: 1 rnn, 4 lstm, 3 gru

    def __init__(self, input_size: int, hidden_size: int,
                 num_layers: int = 1, direction: str = "forward",
                 time_major: bool = False, weight_attr=None):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"unknown direction {direction!r}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bidirectional = direction != "forward"
        self.time_major = time_major
        ndir = 2 if self.bidirectional else 1
        g = self.GATES
        init = weight_attr or I.XavierUniform()
        for lyr in range(num_layers):
            in_sz = input_size if lyr == 0 else hidden_size * ndir
            for d in range(ndir):
                sfx = f"_l{lyr}" + ("_rev" if d else "")
                setattr(self, f"weight_ih{sfx}", self.create_parameter(
                    (in_sz, g * hidden_size), default_initializer=init))
                setattr(self, f"weight_hh{sfx}", self.create_parameter(
                    (hidden_size, g * hidden_size),
                    default_initializer=init))
                setattr(self, f"bias_ih{sfx}", self.create_parameter(
                    (g * hidden_size,), is_bias=True))
                setattr(self, f"bias_hh{sfx}", self.create_parameter(
                    (g * hidden_size,), is_bias=True))

    # cell contract: (carry, x_proj_t) -> (carry, h_t)
    def _cell(self, carry, xp, w_hh, b_hh):
        raise NotImplementedError

    def _init_carry(self, batch):
        h = jnp.zeros((batch, self.hidden_size), jnp.float32)
        return h

    def _carry_from_states(self, initial_states, idx):
        """Slice the [layers*ndir, b, h] state stack(s) for one
        (layer, direction)."""
        if initial_states is None:
            return None
        return initial_states[idx]

    def _run_dir(self, x, sfx, reverse: bool, carry=None):
        # x: [b, s, in] (batch-first internally)
        w_ih = getattr(self, f"weight_ih{sfx}").value
        w_hh = getattr(self, f"weight_hh{sfx}").value
        b_ih = getattr(self, f"bias_ih{sfx}").value
        b_hh = getattr(self, f"bias_hh{sfx}").value
        xp = x @ w_ih + b_ih  # hoisted input projection [b, s, g*h]
        xp = jnp.swapaxes(xp, 0, 1)  # [s, b, g*h] scan over time
        if reverse:
            xp = xp[::-1]
        if carry is None:
            carry = self._init_carry(x.shape[0])

        def step(carry, xpt):
            return self._cell(carry, xpt, w_hh, b_hh)

        last, hs = jax.lax.scan(step, carry, xp)
        if reverse:
            hs = hs[::-1]
        return jnp.swapaxes(hs, 0, 1), last  # [b, s, h], carry

    def forward(self, x, initial_states=None):
        if self.time_major:
            x = jnp.swapaxes(x, 0, 1)
        ndir = 2 if self.bidirectional else 1
        lasts = []
        out = x
        for lyr in range(self.num_layers):
            c0 = self._carry_from_states(initial_states, lyr * ndir)
            fwd, last_f = self._run_dir(out, f"_l{lyr}", reverse=False,
                                        carry=c0)
            if self.bidirectional:
                c1 = self._carry_from_states(initial_states,
                                             lyr * ndir + 1)
                bwd, last_b = self._run_dir(out, f"_l{lyr}_rev",
                                            reverse=True, carry=c1)
                out = jnp.concatenate([fwd, bwd], axis=-1)
                lasts.extend([last_f, last_b])
            else:
                out = fwd
                lasts.append(last_f)
        if self.time_major:
            out = jnp.swapaxes(out, 0, 1)
        return out, self._stack_states(lasts)

    def _stack_states(self, lasts):
        return jnp.stack(lasts, axis=0)  # [layers*ndir, b, h]


class SimpleRNN(_RNNBase):
    """tanh (or relu) Elman RNN."""

    GATES = 1

    def __init__(self, *args, activation: str = "tanh", **kw):
        super().__init__(*args, **kw)
        self.activation = jnp.tanh if activation == "tanh" else jax.nn.relu

    def _cell(self, h, xp, w_hh, b_hh):
        h = self.activation(xp + h @ w_hh + b_hh)
        return h, h


class LSTM(_RNNBase):
    GATES = 4  # i, f, g(cell), o — paddle's gate order (i, f, c, o)

    def _init_carry(self, batch):
        z = jnp.zeros((batch, self.hidden_size), jnp.float32)
        return (z, z)

    def _carry_from_states(self, initial_states, idx):
        if initial_states is None:
            return None
        h, c = initial_states
        return (h[idx], c[idx])

    def _cell(self, carry, xp, w_hh, b_hh):
        h, c = carry
        z = xp + h @ w_hh + b_hh
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c = f * c + i * jnp.tanh(g)
        h = o * jnp.tanh(c)
        return (h, c), h

    def _stack_states(self, lasts):
        hs = jnp.stack([h for h, _ in lasts], axis=0)
        cs = jnp.stack([c for _, c in lasts], axis=0)
        return (hs, cs)


class GRU(_RNNBase):
    GATES = 3  # r(eset), u(pdate), c(andidate) — paddle's order

    def _cell(self, h, xp, w_hh, b_hh):
        hp = h @ w_hh + b_hh
        xr, xu, xc = jnp.split(xp, 3, axis=-1)
        hr, hu, hc = jnp.split(hp, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        u = jax.nn.sigmoid(xu + hu)
        c = jnp.tanh(xc + r * hc)
        h = u * h + (1 - u) * c
        return h, h
