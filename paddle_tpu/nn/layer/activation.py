"""Activation layers (parity: python/paddle/nn/layer/activation.py)."""

from ...core.module import Layer
from .. import functional as F


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class GELU(Layer):
    def __init__(self, approximate=False):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, self.approximate)


class SiLU(Layer):
    def forward(self, x):
        return F.silu(x)


Swish = SiLU


class Sigmoid(Layer):
    def forward(self, x):
        return F.sigmoid(x)


class Tanh(Layer):
    def forward(self, x):
        return F.tanh(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, self.axis)


class Hardswish(Layer):
    def forward(self, x):
        return F.hardswish(x)


class Hardsigmoid(Layer):
    def forward(self, x):
        return F.hardsigmoid(x)


class Mish(Layer):
    def forward(self, x):
        return F.mish(x)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0):
        super().__init__()
        self.beta = beta
        self.threshold = threshold

    def forward(self, x):
        return F.softplus(x, self.beta, self.threshold)


class GLU(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)


class PReLU(Layer):
    """Learnable leaky slope (parity: paddle.nn.PReLU)."""

    def __init__(self, num_parameters=1, init=0.25):
        super().__init__()
        import jax.numpy as jnp

        from ...core import initializer as I

        self.weight = self.create_parameter(
            (num_parameters,), default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight)


class SELU(Layer):
    def forward(self, x):
        return F.selu(x)


class CELU(Layer):
    def __init__(self, alpha=1.0):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class LogSigmoid(Layer):
    def forward(self, x):
        return F.log_sigmoid(x)


class Softsign(Layer):
    def forward(self, x):
        return F.softsign(x)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Tanhshrink(Layer):
    def forward(self, x):
        return F.tanhshrink(x)



class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):  # noqa: A002
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class RReLU(Layer):
    """Randomized leaky ReLU (parity: paddle.nn.RReLU): slope sampled
    U[lower, upper] per element in training, fixed mean slope in eval."""

    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper,
                       training=self.training)
