"""Normalization layers (parity: python/paddle/nn/layer/norm.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import initializer as I
from ...core.module import Layer
from .. import functional as F


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, default_initializer=I.Constant(1.0)
            )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self.normalized_shape, is_bias=True)

    def forward(self, x):
        return F.layer_norm(
            x, self.normalized_shape, self.weight, self.bias, self.epsilon
        )

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}, epsilon={self.epsilon}"


class RMSNorm(Layer):
    """Parity: phi fusion rms_norm / PaddleNLP LlamaRMSNorm."""

    def __init__(self, hidden_size, epsilon=1e-6, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), default_initializer=I.Constant(1.0)
        )

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)

    def extra_repr(self):
        return f"hidden_size={self.hidden_size}, epsilon={self.epsilon}"


class GroupNorm(Layer):
    """``activation`` ("silu" | None) fuses the following nonlinearity
    into the norm — under the NHWC layout policy the fused Pallas
    kernel applies it in the same HBM pass (the UNet's norm→SiLU
    chain); on the NCHW path it is applied functionally, so semantics
    are layout-independent."""

    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None,
                 activation=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        self.activation = activation
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_channels,), default_initializer=I.Constant(1.0)
            )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((num_channels,), is_bias=True)

    def forward(self, x):
        return F.group_norm(
            x, self.num_groups, self.weight, self.bias, self.epsilon,
            self.data_format, activation=self.activation,
        )


class BatchNorm2D(Layer):
    """Batch normalization with running statistics buffers.

    Training-mode batch statistics are computed in fp32; running stats are
    updated eagerly when called outside jit, and treated as frozen inside a
    functional/jitted call (for jit training loops, prefer GroupNorm or
    sync-free norms — the reference's distributed vision configs do the
    same with frozen BN).
    """

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_features,), default_initializer=I.Constant(1.0)
        )
        self.bias = self.create_parameter((num_features,), is_bias=True)
        self.register_buffer("_mean", jnp.zeros((num_features,), jnp.float32))
        self.register_buffer("_variance", jnp.ones((num_features,), jnp.float32))

    def forward(self, x):
        from .. import layout

        df = layout.resolve(self.data_format) if x.ndim == 4 \
            else self.data_format
        c_axis = 1 if df == "NCHW" else -1
        axes = tuple(i for i in range(x.ndim) if i != (c_axis % x.ndim))
        if self.training:
            import jax.core

            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
            if not isinstance(mean, jax.core.Tracer):
                # eager only: under jit the running stats stay frozen so no
                # tracer leaks into the buffers
                self._buffers["_mean"] = (
                    self.momentum * self._buffers["_mean"]
                    + (1 - self.momentum) * mean
                )
                self._buffers["_variance"] = (
                    self.momentum * self._buffers["_variance"]
                    + (1 - self.momentum) * var
                )
        else:
            mean = self._buffers["_mean"]
            var = self._buffers["_variance"]
        shape = [1] * x.ndim
        shape[c_axis % x.ndim] = self.num_features
        xf = x.astype(jnp.float32)
        y = (xf - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + self.epsilon)
        y = y.astype(x.dtype)
        return y * self.weight.value.astype(x.dtype).reshape(shape) + \
            self.bias.value.astype(x.dtype).reshape(shape)


BatchNorm = BatchNorm2D


class InstanceNorm2D(Layer):
    """Per-sample, per-channel normalization over H, W (parity:
    paddle.nn.InstanceNorm2D; stateless — no running stats by default,
    matching the reference's track_running_stats=False semantics)."""

    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        self.num_features = num_features
        self.epsilon = epsilon
        self.data_format = data_format
        self.scale = None if weight_attr is False else \
            self.create_parameter(
                (num_features,), default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else \
            self.create_parameter((num_features,), is_bias=True)

    def forward(self, x):
        from .. import layout

        df = layout.resolve(self.data_format)
        axes = (2, 3) if df == "NCHW" else (1, 2)
        c_axis = 1 if df == "NCHW" else 3
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        y = (xf - mean) / jnp.sqrt(var + self.epsilon)
        shape = [1] * x.ndim
        shape[c_axis] = self.num_features
        if self.scale is not None:
            y = y * self.scale.value.reshape(shape)
        if self.bias is not None:
            y = y + self.bias.value.reshape(shape)
        return y.astype(x.dtype)


class SyncBatchNorm(BatchNorm2D):
    """Cross-replica batch norm.

    On TPU this is BatchNorm2D: inside pjit/GSPMD, ``jnp.mean`` over a
    batch axis that is sharded across the mesh ALREADY reduces globally
    (XLA inserts the all-reduce) — the reference needs an explicit NCCL
    allreduce (paddle/nn/layer/norm.py SyncBatchNorm) only because its
    per-rank eager kernels see local shards. ``convert_sync_batchnorm``
    is therefore an in-place class swap kept for API parity.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for parent in layer.sublayers(include_self=True):
            for name, sub in list(parent._sub_layers.items()):
                if type(sub) is BatchNorm2D:
                    sub.__class__ = cls
        return layer


class LocalResponseNorm(Layer):
    """Parity: paddle.nn.LocalResponseNorm (AlexNet LRN)."""

    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self._args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        size, alpha, beta, k, df = self._args
        return F.local_response_norm(x, size, alpha=alpha, beta=beta,
                                     k=k, data_format=df)


class BatchNorm1D(BatchNorm2D):
    """[N, C] or [N, C, L] input (parity: paddle.nn.BatchNorm1D). The
    base forward derives reduction axes from input rank and from
    whether the format is channels-first, so only the format spelling
    and the expected-rank check differ."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        # base switches on 'NCHW' for channels-first; map the 1-D names
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr,
                         "NCHW" if data_format in ("NCL", "NC", "NCHW")
                         else "NHWC")

    def forward(self, x):
        if x.ndim not in (2, 3):
            raise ValueError(
                f"BatchNorm1D expects 2-D/3-D input, got {x.ndim}-D")
        return super().forward(x)


class BatchNorm3D(BatchNorm2D):
    """[N, C, D, H, W] input (parity: paddle.nn.BatchNorm3D)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr,
                         "NCHW" if data_format in ("NCDHW", "NCHW")
                         else "NHWC")

    def forward(self, x):
        if x.ndim != 5:
            raise ValueError(
                f"BatchNorm3D expects 5-D input, got {x.ndim}-D")
        return super().forward(x)


class SpectralNorm(Layer):
    """Spectral normalization of a WEIGHT tensor passed to forward
    (parity: paddle.nn.SpectralNorm, phi spectral_norm kernel): power
    iteration on W reshaped to 2-D about ``dim``, returning
    W / sigma. u/v persist as buffers across calls the way the
    reference carries them between steps."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        from ...core import random as random_mod

        k1, k2 = jax.random.split(random_mod.next_rng_key("params"))
        self.register_buffer(
            "weight_u", jax.random.normal(k1, (h,), jnp.float32))
        self.register_buffer(
            "weight_v", jax.random.normal(k2, (w,), jnp.float32))

    def forward(self, weight):
        from ..functional.common import _v

        weight = _v(weight)
        perm = [self.dim] + [i for i in range(weight.ndim)
                             if i != self.dim]
        mat = jnp.transpose(weight, perm).reshape(
            weight.shape[self.dim], -1).astype(jnp.float32)
        u = self._buffers["weight_u"]
        v = self._buffers["weight_v"]

        def _l2(x):
            return x / (jnp.linalg.norm(x) + self.eps)

        for _ in range(self.power_iters):
            v = _l2(mat.T @ u)
            u = _l2(mat @ v)
        import jax.core as _core

        if not isinstance(u, _core.Tracer):
            # eager: persist the iteration like the reference kernel
            self._buffers["weight_u"] = u
            self._buffers["weight_v"] = v
        sigma = u @ mat @ v
        return (weight / sigma.astype(weight.dtype)).astype(weight.dtype)
