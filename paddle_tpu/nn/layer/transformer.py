"""Transformer layers (parity: python/paddle/nn/layer/transformer.py).

Layout convention: [batch, seq, hidden]; attention internals use
[batch, seq, heads, head_dim] to match the flash-attention kernel layout.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ...core.module import Layer
from .. import functional as F
from .common import Dropout, Linear
from .norm import LayerNorm


class MultiHeadAttention(Layer):
    def __init__(
        self,
        embed_dim,
        num_heads,
        dropout=0.0,
        kdim=None,
        vdim=None,
        need_weights=False,
        weight_attr=None,
        bias_attr=None,
    ):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def compute_kv(self, key, value):
        """Precompute projected K/V [b, s, h, d] — paddle's StaticCache
        for cross-attention: project the encoder memory ONCE instead of
        per decode step."""
        b = key.shape[0]
        k = self.k_proj(key).reshape(b, key.shape[1], self.num_heads,
                                     self.head_dim)
        v = self.v_proj(value).reshape(b, value.shape[1], self.num_heads,
                                       self.head_dim)
        return k, v

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None, static_cache=None):
        """cache: optional (k_prev, v_prev) with layout [b, s, h, d]
        (parity: paddle MHA Cache for incremental decoding) — current k/v
        are appended and the updated cache returned alongside the output.
        static_cache: precomputed (k, v) from ``compute_kv`` (paddle's
        StaticCache) — key/value projections are skipped entirely."""
        key = query if key is None else key
        value = query if value is None else value
        b, sq, _ = query.shape
        q = self.q_proj(query).reshape(b, sq, self.num_heads, self.head_dim)
        if static_cache is not None:
            k, v = static_cache
        else:
            k, v = self.compute_kv(key, value)
        if cache is not None:
            k_prev, v_prev = cache
            k = jnp.concatenate([k_prev, k], axis=1)
            v = jnp.concatenate([v_prev, v], axis=1)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            training=self.training,
        )
        out = self.out_proj(out.reshape(b, sq, self.embed_dim))
        if cache is not None:
            return out, (k, v)
        return out


class TransformerEncoderLayer(Layer):
    def __init__(
        self,
        d_model,
        nhead,
        dim_feedforward,
        dropout=0.1,
        activation="relu",
        attn_dropout=None,
        act_dropout=None,
        normalize_before=False,
    ):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout if attn_dropout is not None else dropout
        )
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(
            act_dropout if act_dropout is not None else dropout
        )
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = self.self_attn(src, attn_mask=src_mask)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer_fn, num_layers, norm=None):
        super().__init__()
        from .common import LayerList

        if callable(encoder_layer_fn) and not isinstance(encoder_layer_fn, Layer):
            self.layers = LayerList([encoder_layer_fn() for _ in range(num_layers)])
        else:
            # paddle passes a prototype layer; we deep-construct fresh ones is
            # not possible without config, so accept list
            raise TypeError(
                "pass a factory callable: TransformerEncoder(lambda: "
                "TransformerEncoderLayer(...), num_layers)"
            )
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out


class TransformerDecoderLayer(Layer):
    """Parity: paddle.nn.TransformerDecoderLayer — masked self-attention,
    encoder-decoder cross-attention, FFN, each with pre-/post-LN."""

    def __init__(
        self,
        d_model,
        nhead,
        dim_feedforward,
        dropout=0.1,
        activation="relu",
        attn_dropout=None,
        act_dropout=None,
        normalize_before=False,
    ):
        super().__init__()
        self.normalize_before = normalize_before
        ad = attn_dropout if attn_dropout is not None else dropout
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=ad)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=ad)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(
            act_dropout if act_dropout is not None else dropout
        )
        self.activation = getattr(F, activation)

    def gen_static_cache(self, memory):
        """Precompute the cross-attention K/V for ``memory`` (paddle's
        StaticCache) — call once per sequence, pass to every step."""
        return self.cross_attn.compute_kv(memory, memory)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None, static_cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is not None:
            tgt, new_cache = self.self_attn(tgt, attn_mask=tgt_mask,
                                            cache=cache)
        else:
            tgt = self.self_attn(tgt, attn_mask=tgt_mask)
            new_cache = None
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, attn_mask=memory_mask,
                              static_cache=static_cache)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.act_dropout(self.activation(
            self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return (tgt, new_cache) if cache is not None else tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer_fn, num_layers, norm=None):
        super().__init__()
        from .common import LayerList

        if callable(decoder_layer_fn) and not isinstance(
                decoder_layer_fn, Layer):
            self.layers = LayerList(
                [decoder_layer_fn() for _ in range(num_layers)])
        else:
            raise TypeError(
                "pass a factory callable: TransformerDecoder(lambda: "
                "TransformerDecoderLayer(...), num_layers)")
        self.num_layers = num_layers
        self.norm = norm

    def gen_static_cache(self, memory):
        """Per-layer precomputed cross-attention K/V (StaticCache)."""
        return [layer.gen_static_cache(memory) for layer in self.layers]

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None, static_cache=None):
        """``cache``: optional list of per-layer (k, v) self-attention
        caches (parity: paddle TransformerDecoder incremental decode) —
        returns (out, new_caches) when given. ``static_cache``: per-layer
        precomputed cross-attention K/V from ``gen_static_cache`` so the
        encoder memory is projected once per sequence, not per step."""
        out = tgt
        new_caches = [] if cache is not None else None
        for i, layer in enumerate(self.layers):
            sc = static_cache[i] if static_cache is not None else None
            if cache is not None:
                out, c = layer(out, memory, tgt_mask=tgt_mask,
                               memory_mask=memory_mask, cache=cache[i],
                               static_cache=sc)
                new_caches.append(c)
            else:
                out = layer(out, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask, static_cache=sc)
        if self.norm is not None:
            out = self.norm(out)
        return (out, new_caches) if cache is not None else out


class Transformer(Layer):
    """Parity: paddle.nn.Transformer — the full encoder-decoder seq2seq
    stack. ``generate_square_subsequent_mask`` matches paddle's helper."""

    def __init__(
        self,
        d_model=512,
        nhead=8,
        num_encoder_layers=6,
        num_decoder_layers=6,
        dim_feedforward=2048,
        dropout=0.1,
        activation="relu",
        attn_dropout=None,
        act_dropout=None,
        normalize_before=False,
    ):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        # paddle constructs the final encoder/decoder LayerNorms
        # unconditionally (both pre- and post-LN configs)
        self.encoder = TransformerEncoder(
            lambda: TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before),
            num_encoder_layers, norm=LayerNorm(d_model))
        self.decoder = TransformerDecoder(
            lambda: TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before),
            num_decoder_layers, norm=LayerNorm(d_model))

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        """Additive float [length, length] causal mask (0 = attend,
        -inf = masked) — paddle's convention; scaled_dot_product_attention
        also accepts boolean masks, so both styles work downstream."""
        allow = jnp.tril(jnp.ones((length, length), bool))
        return jnp.where(allow, 0.0, -jnp.inf).astype(jnp.float32)
