"""Transformer layers (parity: python/paddle/nn/layer/transformer.py).

Layout convention: [batch, seq, hidden]; attention internals use
[batch, seq, heads, head_dim] to match the flash-attention kernel layout.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ...core.module import Layer
from .. import functional as F
from .common import Dropout, Linear
from .norm import LayerNorm


class MultiHeadAttention(Layer):
    def __init__(
        self,
        embed_dim,
        num_heads,
        dropout=0.0,
        kdim=None,
        vdim=None,
        need_weights=False,
        weight_attr=None,
        bias_attr=None,
    ):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        """cache: optional (k_prev, v_prev) with layout [b, s, h, d]
        (parity: paddle MHA Cache for incremental decoding) — current k/v
        are appended and the updated cache returned alongside the output."""
        key = query if key is None else key
        value = query if value is None else value
        b, sq, _ = query.shape
        q = self.q_proj(query).reshape(b, sq, self.num_heads, self.head_dim)
        k = self.k_proj(key).reshape(b, key.shape[1], self.num_heads, self.head_dim)
        v = self.v_proj(value).reshape(b, value.shape[1], self.num_heads, self.head_dim)
        if cache is not None:
            k_prev, v_prev = cache
            k = jnp.concatenate([k_prev, k], axis=1)
            v = jnp.concatenate([v_prev, v], axis=1)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout,
            training=self.training,
        )
        out = self.out_proj(out.reshape(b, sq, self.embed_dim))
        if cache is not None:
            return out, (k, v)
        return out


class TransformerEncoderLayer(Layer):
    def __init__(
        self,
        d_model,
        nhead,
        dim_feedforward,
        dropout=0.1,
        activation="relu",
        attn_dropout=None,
        act_dropout=None,
        normalize_before=False,
    ):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout if attn_dropout is not None else dropout
        )
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(
            act_dropout if act_dropout is not None else dropout
        )
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = self.self_attn(src, attn_mask=src_mask)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer_fn, num_layers, norm=None):
        super().__init__()
        from .common import LayerList

        if callable(encoder_layer_fn) and not isinstance(encoder_layer_fn, Layer):
            self.layers = LayerList([encoder_layer_fn() for _ in range(num_layers)])
        else:
            # paddle passes a prototype layer; we deep-construct fresh ones is
            # not possible without config, so accept list
            raise TypeError(
                "pass a factory callable: TransformerEncoder(lambda: "
                "TransformerEncoderLayer(...), num_layers)"
            )
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out
