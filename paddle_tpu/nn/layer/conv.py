"""Convolution and pooling layers (parity: python/paddle/nn/layer/conv.py,
pooling.py)."""

from __future__ import annotations

from ...core import initializer as I
from ...core.module import Layer
from .. import functional as F


class Conv2D(Layer):
    """Weight layout [out_channels, in_channels/groups, kh, kw]."""

    def __init__(
        self,
        in_channels,
        out_channels,
        kernel_size,
        stride=1,
        padding=0,
        dilation=1,
        groups=1,
        weight_attr=None,
        bias_attr=None,
        data_format="NCHW",
    ):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, *kernel_size),
            default_initializer=weight_attr or I.KaimingUniform(),
        )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((out_channels,), is_bias=True)

    def forward(self, x):
        return F.conv2d(
            x, self.weight, self.bias, self.stride, self.padding,
            self.dilation, self.groups, self.data_format,
        )

    def extra_repr(self):
        return (
            f"{self.in_channels}, {self.out_channels}, "
            f"kernel_size={self.kernel_size}, stride={self.stride}"
        )


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW"):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(
            x, self.kernel_size, self.stride, self.padding, self.data_format
        )


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW"):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(
            x, self.kernel_size, self.stride, self.padding, self.data_format
        )


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW"):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class _ConvNd(Layer):
    """Shared constructor for Conv1D/Conv3D (weight [out, in/g, *k])."""

    NDIM = 1

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format=None):
        super().__init__()
        nd = self.NDIM
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * nd
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, *kernel_size),
            default_initializer=weight_attr or I.KaimingUniform(),
        )
        self.bias = None if bias_attr is False else \
            self.create_parameter((out_channels,), is_bias=True)


class Conv1D(_ConvNd):
    NDIM = 1

    def __init__(self, *a, data_format="NCL", **kw):
        super().__init__(*a, data_format=data_format, **kw)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride,
                        self.padding, self.dilation, self.groups,
                        self.data_format)


class Conv3D(_ConvNd):
    NDIM = 3

    def __init__(self, *a, data_format="NCDHW", **kw):
        super().__init__(*a, data_format=data_format, **kw)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride,
                        self.padding, self.dilation, self.groups,
                        self.data_format)


class Conv2DTranspose(Layer):
    """Weight layout [in_channels, out_channels/groups, kh, kw]."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups, *kernel_size),
            default_initializer=weight_attr or I.KaimingUniform(),
        )
        self.bias = None if bias_attr is False else \
            self.create_parameter((out_channels,), is_bias=True)

    def forward(self, x):
        return F.conv2d_transpose(
            x, self.weight, self.bias, self.stride, self.padding,
            self.output_padding, self.dilation, self.groups,
            self.data_format)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride or kernel_size
        self.padding = padding

    def forward(self, x):
        # [b, c, l] → window-reduce over the trailing dim
        import jax.numpy as jnp
        from jax import lax

        pads = ((0, 0), (0, 0), (self.padding, self.padding))
        ident = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        return lax.reduce_window(
            x, ident, lax.max,
            (1, 1, self.kernel_size), (1, 1, self.stride), pads)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride or kernel_size
        self.padding = padding

    def forward(self, x):
        import jax.numpy as jnp
        from jax import lax

        pads = ((0, 0), (0, 0), (self.padding, self.padding))
        win = (1, 1, self.kernel_size)
        strides = (1, 1, self.stride)
        s = lax.reduce_window(x, 0.0, lax.add, win, strides, pads)
        # exclusive divisor: count only real (non-pad) elements per
        # window — matches avg_pool2d and the reference's exclusive=True
        cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, win,
                                strides, pads)
        return s / cnt


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW"):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride or kernel_size
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride,
                            self.padding, self.data_format)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW"):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride or kernel_size
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride,
                            self.padding, self.data_format)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW"):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size,
                                     self.data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False,
                 data_format="NCHW"):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size,
                                     self.return_mask, self.data_format)


class Conv1DTranspose(Layer):
    """Weight layout [in_channels, out_channels/groups, k]."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,)
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups, *kernel_size),
            default_initializer=weight_attr or I.KaimingUniform(),
        )
        self.bias = None if bias_attr is False else \
            self.create_parameter((out_channels,), is_bias=True)

    def forward(self, x):
        return F.conv1d_transpose(
            x, self.weight, self.bias, self.stride, self.padding,
            self.output_padding, self.groups, self.dilation,
            self.data_format)


class Conv3DTranspose(Layer):
    """Weight layout [in_channels, out_channels/groups, kd, kh, kw]."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * 3
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups, *kernel_size),
            default_initializer=weight_attr or I.KaimingUniform(),
        )
        self.bias = None if bias_attr is False else \
            self.create_parameter((out_channels,), is_bias=True)

    def forward(self, x):
        return F.conv3d_transpose(
            x, self.weight, self.bias, self.stride, self.padding,
            self.output_padding, self.groups, self.dilation,
            self.data_format)
