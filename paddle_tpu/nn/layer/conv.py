"""Convolution and pooling layers (parity: python/paddle/nn/layer/conv.py,
pooling.py)."""

from __future__ import annotations

from ...core import initializer as I
from ...core.module import Layer
from .. import functional as F


class Conv2D(Layer):
    """Weight layout [out_channels, in_channels/groups, kh, kw]."""

    def __init__(
        self,
        in_channels,
        out_channels,
        kernel_size,
        stride=1,
        padding=0,
        dilation=1,
        groups=1,
        weight_attr=None,
        bias_attr=None,
        data_format="NCHW",
    ):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, *kernel_size),
            default_initializer=weight_attr or I.KaimingUniform(),
        )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((out_channels,), is_bias=True)

    def forward(self, x):
        return F.conv2d(
            x, self.weight, self.bias, self.stride, self.padding,
            self.dilation, self.groups, self.data_format,
        )

    def extra_repr(self):
        return (
            f"{self.in_channels}, {self.out_channels}, "
            f"kernel_size={self.kernel_size}, stride={self.stride}"
        )


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW"):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(
            x, self.kernel_size, self.stride, self.padding, self.data_format
        )


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW"):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(
            x, self.kernel_size, self.stride, self.padding, self.data_format
        )


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW"):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)
