"""Common layers (parity: python/paddle/nn/layer/common.py)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ...core import initializer as I
from ...core.module import Layer
from .. import functional as F


class Identity(Layer):
    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b with weight [in_features, out_features] (paddle layout,
    upstream python/paddle/nn/layer/common.py::Linear)."""

    def __init__(
        self,
        in_features: int,
        out_features: int,
        weight_attr=None,
        bias_attr=None,
        name=None,
    ):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), default_initializer=weight_attr
        )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_features,), is_bias=True, default_initializer=None
                if bias_attr in (None, True) else bias_attr
            )

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    """Parity: paddle.nn.Embedding; weight [num_embeddings, embedding_dim]."""

    def __init__(
        self,
        num_embeddings: int,
        embedding_dim: int,
        padding_idx: Optional[int] = None,
        sparse: bool = False,
        weight_attr=None,
        name=None,
    ):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim),
            default_initializer=weight_attr or I.Normal(0.0, 1.0),
        )
        if padding_idx is not None:
            self.weight.value = self.weight.value.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and layers[0] and isinstance(layers[0][0], tuple):
            # paddle style: Sequential(('name', layer), ...)
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, layer in enumerate(sublayers):
                self.add_sublayer(str(i), layer)

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def extend(self, layers):
        for layer in layers:
            self.append(layer)
        return self

    def insert(self, index, layer):
        existing = list(self._sub_layers.values())
        existing.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(existing):
            self._sub_layers[str(i)] = l

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        if idx < 0:
            idx += len(self._sub_layers)
        return self._sub_layers[str(idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        import math

        shape = x.shape
        stop = self.stop_axis if self.stop_axis >= 0 else len(shape) + self.stop_axis
        # host arithmetic on the STATIC dims — a jnp.prod here would
        # trace to a device op and break int() under jit
        new_shape = (
            shape[: self.start_axis]
            + (math.prod(shape[self.start_axis: stop + 1]),)
            + shape[stop + 1:]
        )
        return x.reshape(new_shape)


class Bilinear(Layer):
    """out[.., o] = x1 @ W[o] @ x2 + b (parity: paddle.nn.Bilinear)."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features),
            default_initializer=weight_attr or I.XavierUniform(),
        )
        self.bias = None if bias_attr is False else \
            self.create_parameter((out_features,), is_bias=True)

    def forward(self, x1, x2):
        y = jnp.einsum("bi,oij,bj->bo", x1, self.weight.value, x2)
        if self.bias is not None:
            y = y + self.bias.value
        return y


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW"):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


_PAD_MODE = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap", "edge": "edge",
             "wrap": "wrap"}


def _np_pad_mode(mode):
    """Paddle pad-mode names -> numpy/jnp.pad names (replicate->edge,
    circular->wrap); unknown names raise up front."""
    try:
        return _PAD_MODE[mode]
    except KeyError:
        raise ValueError(f"unsupported pad mode {mode!r}") from None


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW"):
        super().__init__()
        if isinstance(padding, int):
            padding = [padding] * 4
        self.padding = padding  # [left, right, top, bottom] (paddle order)
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        l, r, t, b = self.padding
        if self.data_format == "NCHW":
            pads = ((0, 0), (0, 0), (t, b), (l, r))
        else:
            pads = ((0, 0), (t, b), (l, r), (0, 0))
        if self.mode == "constant":
            return jnp.pad(x, pads, constant_values=self.value)
        return jnp.pad(x, pads, mode=_np_pad_mode(self.mode))


class Dropout2D(Layer):
    """Drops whole channels (parity: paddle.nn.Dropout2D)."""

    def __init__(self, p=0.5, data_format="NCHW"):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        if not self.training or self.p == 0.0:
            return x
        from ...core import random as random_mod
        import jax

        key = random_mod.next_rng_key("dropout2d")
        shape = list(x.shape)
        if self.data_format == "NCHW":
            shape[2] = shape[3] = 1
        else:
            shape[1] = shape[2] = 1
        keep = jax.random.bernoulli(key, 1.0 - self.p, shape)
        return jnp.where(keep, x / (1.0 - self.p), 0.0)


class CosineSimilarity(Layer):
    def __init__(self, axis=-1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        d = jnp.abs(x - y) + self.epsilon
        out = jnp.sum(d ** self.p, axis=-1) ** (1.0 / self.p)
        return out[..., None] if self.keepdim else out


class Unflatten(Layer):
    def __init__(self, axis, shape):
        super().__init__()
        self.axis = axis
        self.shape = tuple(shape)

    def forward(self, x):
        ax = self.axis % x.ndim
        return x.reshape(x.shape[:ax] + self.shape + x.shape[ax + 1:])


class Upsample(Layer):
    """Parity: paddle.nn.Upsample over F.interpolate."""

    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW"):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        from .. import functional as F

        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             align_corners=self.align_corners,
                             align_mode=self.align_mode,
                             data_format=self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__(size, scale_factor, "nearest",
                         data_format=data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW"):
        super().__init__(size, scale_factor, "bilinear",
                         align_corners=True, data_format=data_format)


class Unfold(Layer):
    """Parity: paddle.nn.Unfold (im2col)."""

    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self._args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        k, s, p, d = self._args
        return F.unfold(x, k, strides=s, paddings=p, dilations=d)


class Fold(Layer):
    """Parity: paddle.nn.Fold (col2im)."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self._args = (output_sizes, kernel_sizes, strides, paddings,
                      dilations)

    def forward(self, x):
        o, k, s, p, d = self._args
        return F.fold(x, o, k, strides=s, paddings=p, dilations=d)


class AlphaDropout(Layer):
    """Parity: paddle.nn.AlphaDropout (SELU-preserving dropout)."""

    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class ZeroPad2D(Layer):
    """Parity: paddle.nn.ZeroPad2D — padding [left, right, top, bottom]."""

    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        return F.zeropad2d(x, self.padding, self.data_format)


class Dropout3D(Layer):
    """Drops whole channels of 5-D input (parity: paddle.nn.Dropout3D)."""

    def __init__(self, p=0.5, data_format="NCDHW"):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class Pad1D(Layer):
    """[left, right] padding on [N, C, L] (parity: paddle.nn.Pad1D)."""

    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL"):
        super().__init__()
        if isinstance(padding, int):
            padding = [padding] * 2
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        l, r = self.padding
        pads = ((0, 0), (0, 0), (l, r)) if self.data_format == "NCL" \
            else ((0, 0), (l, r), (0, 0))
        if self.mode == "constant":
            return jnp.pad(x, pads, constant_values=self.value)
        return jnp.pad(x, pads, mode=_np_pad_mode(self.mode))


class Pad3D(Layer):
    """[left, right, top, bottom, front, back] on [N, C, D, H, W]
    (parity: paddle.nn.Pad3D)."""

    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW"):
        super().__init__()
        if isinstance(padding, int):
            padding = [padding] * 6
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        l, r, t, b, f, bk = self.padding
        if self.data_format == "NCDHW":
            pads = ((0, 0), (0, 0), (f, bk), (t, b), (l, r))
        else:
            pads = ((0, 0), (f, bk), (t, b), (l, r), (0, 0))
        if self.mode == "constant":
            return jnp.pad(x, pads, constant_values=self.value)
        return jnp.pad(x, pads, mode=_np_pad_mode(self.mode))


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW"):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor,
                                 self.data_format)


class LayerDict(Layer):
    """Dict-style sublayer container (parity: paddle.nn.LayerDict)."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, sublayer):
        self.add_sublayer(str(key), sublayer)

    def __delitem__(self, key):
        del self._sub_layers[str(key)]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        v = self._sub_layers[key]
        del self._sub_layers[key]
        return v

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        if isinstance(sublayers, dict):
            sublayers = sublayers.items()
        for k, v in sublayers:
            self.add_sublayer(str(k), v)
