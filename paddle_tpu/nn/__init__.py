"""paddle_tpu.nn — layers and functional ops (parity: paddle.nn)."""

from ..core.module import Layer
from ..core.parameter import Parameter
from . import functional
from .layer.activation import (
    CELU,
    ELU,
    GELU,
    GLU,
    Hardshrink,
    Hardsigmoid,
    Hardswish,
    LeakyReLU,
    LogSigmoid,
    LogSoftmax,
    Mish,
    PReLU,
    ReLU,
    ReLU6,
    SELU,
    Sigmoid,
    SiLU,
    Softmax,
    Softplus,
    Softshrink,
    Softsign,
    Swish,
    Tanh,
    Tanhshrink,
    ThresholdedReLU,
)
from .layer.common import (
    Bilinear,
    CosineSimilarity,
    Dropout,
    Dropout2D,
    Embedding,
    Flatten,
    Identity,
    LayerList,
    Linear,
    Pad2D,
    PairwiseDistance,
    ParameterList,
    PixelShuffle,
    Sequential,
    Unflatten,
    Upsample,
)
from .layer.conv import (
    AdaptiveAvgPool2D,
    AvgPool1D,
    AvgPool2D,
    Conv1D,
    Conv2D,
    Conv2DTranspose,
    Conv3D,
    MaxPool1D,
    MaxPool2D,
)
from .layer.loss import (
    BCEWithLogitsLoss,
    CrossEntropyLoss,
    HuberLoss,
    KLDivLoss,
    L1Loss,
    MarginRankingLoss,
    MSELoss,
    NLLLoss,
    SmoothL1Loss,
)
from .layer.norm import (
    BatchNorm,
    BatchNorm2D,
    GroupNorm,
    InstanceNorm2D,
    LayerNorm,
    RMSNorm,
    SyncBatchNorm,
)
from .layer.rnn import GRU, LSTM, SimpleRNN
from .layer.transformer import (
    MultiHeadAttention,
    TransformerEncoder,
    TransformerEncoderLayer,
)

__all__ = [
    "Layer", "Parameter", "functional",
    "Linear", "Embedding", "Dropout", "Dropout2D", "Identity", "Sequential",
    "LayerList", "ParameterList", "Flatten", "Unflatten", "Upsample",
    "Bilinear", "PixelShuffle", "Pad2D", "CosineSimilarity",
    "PairwiseDistance",
    "ReLU", "ReLU6", "GELU", "SiLU", "Swish", "Sigmoid", "Tanh", "LeakyReLU",
    "ELU", "CELU", "SELU", "PReLU", "Softmax", "LogSoftmax", "LogSigmoid",
    "Hardswish", "Hardsigmoid", "Hardshrink", "Softshrink", "Tanhshrink",
    "Softsign", "ThresholdedReLU", "Mish", "Softplus", "GLU",
    "LayerNorm", "RMSNorm", "GroupNorm", "BatchNorm", "BatchNorm2D",
    "InstanceNorm2D", "SyncBatchNorm",
    "Conv1D", "Conv2D", "Conv3D", "Conv2DTranspose",
    "MaxPool1D", "MaxPool2D", "AvgPool1D", "AvgPool2D", "AdaptiveAvgPool2D",
    "SimpleRNN", "LSTM", "GRU",
    "CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCEWithLogitsLoss",
    "SmoothL1Loss", "HuberLoss", "KLDivLoss", "MarginRankingLoss",
    "MultiHeadAttention", "TransformerEncoder", "TransformerEncoderLayer",
]
