"""paddle_tpu.nn — layers and functional ops (parity: paddle.nn)."""

from ..core.module import Layer
from ..core.parameter import Parameter
from . import functional
from .layer.activation import (
    ELU,
    GELU,
    GLU,
    Hardsigmoid,
    Hardswish,
    LeakyReLU,
    LogSoftmax,
    Mish,
    ReLU,
    ReLU6,
    Sigmoid,
    SiLU,
    Softmax,
    Softplus,
    Swish,
    Tanh,
)
from .layer.common import (
    Dropout,
    Embedding,
    Flatten,
    Identity,
    LayerList,
    Linear,
    ParameterList,
    Sequential,
    Upsample,
)
from .layer.conv import AdaptiveAvgPool2D, AvgPool2D, Conv2D, MaxPool2D
from .layer.loss import (
    BCEWithLogitsLoss,
    CrossEntropyLoss,
    L1Loss,
    MSELoss,
    NLLLoss,
)
from .layer.norm import (
    BatchNorm,
    BatchNorm2D,
    GroupNorm,
    LayerNorm,
    RMSNorm,
)
from .layer.transformer import (
    MultiHeadAttention,
    TransformerEncoder,
    TransformerEncoderLayer,
)

__all__ = [
    "Layer", "Parameter", "functional",
    "Linear", "Embedding", "Dropout", "Identity", "Sequential", "LayerList",
    "ParameterList", "Flatten", "Upsample",
    "ReLU", "ReLU6", "GELU", "SiLU", "Swish", "Sigmoid", "Tanh", "LeakyReLU",
    "ELU", "Softmax", "LogSoftmax", "Hardswish", "Hardsigmoid", "Mish",
    "Softplus", "GLU",
    "LayerNorm", "RMSNorm", "GroupNorm", "BatchNorm", "BatchNorm2D",
    "Conv2D", "MaxPool2D", "AvgPool2D", "AdaptiveAvgPool2D",
    "CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCEWithLogitsLoss",
    "MultiHeadAttention", "TransformerEncoder", "TransformerEncoderLayer",
]
