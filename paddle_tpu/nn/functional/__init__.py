"""Functional ops (parity: paddle.nn.functional).

Thin, jit-friendly wrappers over jax.numpy/lax. Where the reference routes
through hand-written CUDA kernels (paddle/phi/kernels/gpu/,
paddle/phi/kernels/fusion/), XLA fusion covers the same ground on TPU; the
genuinely hot fused paths (flash attention, rope/rmsnorm at long seq,
paged decode) live in paddle_tpu.kernels as Pallas implementations and are
dispatched from here when available.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...core import random as random_mod
from ...core.parameter import Parameter


def _v(x):
    return x.value if isinstance(x, Parameter) else x

def _f32up(x):
    """Upcast to AT LEAST float32 for stable statistics — never downcast
    (fp64 inputs, e.g. the OpTest finite-difference harness, stay fp64)."""
    return x.astype(jnp.promote_types(x.dtype, jnp.float32))


# ---------------------------------------------------------------------------
# linear / embedding
# ---------------------------------------------------------------------------
def linear(x, weight, bias=None):
    """y = x @ W (+ b). Weight layout [in_features, out_features] (paddle
    convention, phi kernel matmul_kernel)."""
    x, weight = _v(x), _v(weight)
    y = jnp.matmul(x, weight)
    if bias is not None:
        y = y + _v(bias)
    return y


def embedding(x, weight, padding_idx=None):
    x, weight = _v(x), _v(weight)
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros((), out.dtype), out)
    return out


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------
def relu(x):
    return jax.nn.relu(_v(x))


def relu6(x):
    return jax.nn.relu6(_v(x))


def gelu(x, approximate=False):
    return jax.nn.gelu(_v(x), approximate=approximate)


def silu(x):
    return jax.nn.silu(_v(x))


swish = silu


def sigmoid(x):
    return jax.nn.sigmoid(_v(x))


def tanh(x):
    return jnp.tanh(_v(x))


def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(_v(x), negative_slope)


def elu(x, alpha=1.0):
    return jax.nn.elu(_v(x), alpha)


def softplus(x, beta=1.0, threshold=20.0):
    return jax.nn.softplus(_v(x) * beta) / beta


def hardswish(x):
    return jax.nn.hard_swish(_v(x))


def hardsigmoid(x):
    x = _v(x)
    return jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


def mish(x):
    return jax.nn.mish(_v(x))


def softmax(x, axis=-1):
    return jax.nn.softmax(_v(x), axis=axis)


def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(_v(x), axis=axis)


def glu(x, axis=-1):
    return jax.nn.glu(_v(x), axis=axis)


def swiglu(x, y=None):
    """Parity: phi fusion swiglu — silu(x) * y (split x in half if y None)."""
    x = _v(x)
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * _v(y)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------
def layer_norm(x, normalized_shape=None, weight=None, bias=None, epsilon=1e-5):
    x = _v(x)
    # compute statistics in fp32 for bf16 inputs (parity: phi layer_norm
    # kernel accumulates in float)
    xf = _f32up(x)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + epsilon)
    y = y.astype(x.dtype)
    if weight is not None:
        y = y * _v(weight)
    if bias is not None:
        y = y + _v(bias)
    return y


def rms_norm(x, weight=None, epsilon=1e-6):
    """Parity: phi fusion rms_norm kernel."""
    x = _v(x)
    xf = _f32up(x)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = (xf * lax.rsqrt(var + epsilon)).astype(x.dtype)
    if weight is not None:
        y = y * _v(weight)
    return y


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5, data_format="NCHW"):
    x = _v(x)
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    g = num_groups
    xf = _f32up(x).reshape(n, g, c // g, *spatial)
    axes = tuple(range(2, xf.ndim))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = ((xf - mean) * lax.rsqrt(var + epsilon)).reshape(n, c, *spatial).astype(x.dtype)
    if weight is not None:
        y = y * _v(weight).reshape(1, c, *([1] * len(spatial)))
    if bias is not None:
        y = y + _v(bias).reshape(1, c, *([1] * len(spatial)))
    if data_format == "NHWC":
        y = jnp.moveaxis(y, 1, -1)
    return y


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------
def dropout(x, p=0.5, training=True, mode="upscale_in_train", rng_key=None):
    x = _v(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x * (1.0 - p)
        return x
    if p == 1.0:
        return jnp.zeros_like(x)
    key = rng_key if rng_key is not None else random_mod.next_rng_key("dropout")
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, jnp.zeros((), x.dtype)).astype(x.dtype)
    return jnp.where(mask, x, jnp.zeros((), x.dtype))


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------
def cross_entropy(
    logits,
    label,
    soft_label: bool = False,
    ignore_index: int = -100,
    reduction: str = "mean",
    axis: int = -1,
    label_smoothing: float = 0.0,
):
    """Parity: F.cross_entropy (softmax_with_cross_entropy phi kernel).

    Computes in fp32 regardless of input dtype (matching the fused kernel's
    accumulation behavior).
    """
    logits = _f32up(_v(logits))
    if axis not in (-1, logits.ndim - 1):
        # normalize to class-dim-last so gathers/one-hots line up
        logits = jnp.moveaxis(logits, axis, -1)
        if soft_label:
            label = jnp.moveaxis(_v(label), axis, -1)
        axis = -1
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        target = _v(label).astype(logits.dtype)
        loss = -jnp.sum(target * logp, axis=axis)
        valid = jnp.ones(loss.shape, jnp.float32)
    else:
        label = _v(label)
        num_classes = logits.shape[axis]
        if label_smoothing > 0.0:
            onehot = jax.nn.one_hot(label, num_classes, dtype=jnp.float32)
            smooth = (
                onehot * (1.0 - label_smoothing) + label_smoothing / num_classes
            )
            loss = -jnp.sum(smooth * logp, axis=axis)
        else:
            safe_label = jnp.where(label == ignore_index, 0, label)
            loss = -jnp.take_along_axis(
                logp, safe_label[..., None], axis=axis
            ).squeeze(axis)
        valid = (label != ignore_index).astype(jnp.float32)
        loss = loss * valid
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    denom = jnp.maximum(jnp.sum(valid), 1.0)
    return jnp.sum(loss) / denom


def mse_loss(input, label, reduction="mean"):  # noqa: A002
    d = (_v(input) - _v(label)) ** 2
    if reduction == "none":
        return d
    return jnp.sum(d) if reduction == "sum" else jnp.mean(d)


def l1_loss(input, label, reduction="mean"):  # noqa: A002
    d = jnp.abs(_v(input) - _v(label))
    if reduction == "none":
        return d
    return jnp.sum(d) if reduction == "sum" else jnp.mean(d)


def nll_loss(log_probs, label, reduction="mean", ignore_index=-100):
    logp = _v(log_probs)
    label = _v(label)
    safe = jnp.where(label == ignore_index, 0, label)
    loss = -jnp.take_along_axis(logp, safe[..., None], axis=-1).squeeze(-1)
    valid = (label != ignore_index).astype(loss.dtype)
    loss = loss * valid
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1.0)


def binary_cross_entropy_with_logits(logits, label, reduction="mean"):
    logits = _f32up(_v(logits))
    label = _v(label).astype(logits.dtype)
    loss = jnp.maximum(logits, 0) - logits * label + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    if reduction == "none":
        return loss
    return jnp.sum(loss) if reduction == "sum" else jnp.mean(loss)


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------
def scaled_dot_product_attention(
    query,
    key,
    value,
    attn_mask=None,
    dropout_p: float = 0.0,
    is_causal: bool = False,
    scale: Optional[float] = None,
    training: bool = True,
):
    """Reference attention in pure XLA. Layout: [batch, seq, heads, dim]
    (paddle flash_attention layout, phi flash_attn kernel).

    The Pallas flash-attention kernel (paddle_tpu.kernels.flash_attention)
    is preferred on TPU for long sequences; this is the numerics reference
    and the general fallback (arbitrary masks, GQA).
    """
    q, k, v = _v(query), _v(key), _v(value)
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    if hq != hk:  # grouped-query attention: repeat kv heads
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else d ** -0.5
    # [b, h, sq, sk]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = _f32up(logits)
    if is_causal:
        sk = k.shape[1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(causal, logits, jnp.float32(-1e30))
    if attn_mask is not None:
        m = _v(attn_mask)
        if m.dtype == jnp.bool_:
            logits = jnp.where(m, logits, jnp.float32(-1e30))
        else:
            logits = logits + m.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        probs = dropout(probs, dropout_p, training=True)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def flash_attention(
    query, key, value, dropout=0.0, causal=False, *, training=True, **kw
):
    """Parity: paddle.nn.functional.flash_attention.flash_attention.

    Dispatches to the Pallas TPU kernel when running on TPU with supported
    shapes, else the XLA reference path.
    """
    from ...kernels import flash_attention as fa

    return fa.flash_attention(
        _v(query), _v(key), _v(value), causal=causal,
        dropout_p=dropout, training=training,
    )


# ---------------------------------------------------------------------------
# conv / pool
# ---------------------------------------------------------------------------
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    """Weight layout [out_c, in_c/groups, kh, kw] (paddle convention)."""
    x, weight = _v(x), _v(weight)
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    elif isinstance(padding, str):
        padding = padding.upper()
    elif isinstance(padding, (list, tuple)) and len(padding) == 2 and all(
        isinstance(p, int) for p in padding
    ):
        padding = [(padding[0], padding[0]), (padding[1], padding[1])]
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "OIHW", "NHWC"),
    )
    y = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.bfloat16 else None,
    )
    y = y.astype(x.dtype)
    if bias is not None:
        b = _v(bias)
        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        y = y + b.reshape(shape)
    return y


def max_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW"):
    x = _v(x)
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    if data_format == "NCHW":
        window = (1, 1) + tuple(kernel_size)
        strides = (1, 1) + tuple(stride)
        pads = [(0, 0), (0, 0)] + list(padding)
    else:
        window = (1,) + tuple(kernel_size) + (1,)
        strides = (1,) + tuple(stride) + (1,)
        pads = [(0, 0)] + list(padding) + [(0, 0)]
    return lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max, window, strides, pads,
    )


def avg_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW"):
    x = _v(x)
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    if data_format == "NCHW":
        window = (1, 1) + tuple(kernel_size)
        strides = (1, 1) + tuple(stride)
        pads = [(0, 0), (0, 0)] + list(padding)
    else:
        window = (1,) + tuple(kernel_size) + (1,)
        strides = (1,) + tuple(stride) + (1,)
        pads = [(0, 0)] + list(padding) + [(0, 0)]
    summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    counts = lax.reduce_window(
        jnp.ones_like(x), 0.0, lax.add, window, strides, pads
    )
    return summed / counts


def _adaptive_avg_matrix(out_len, in_len):
    """[out, in] row-stochastic bin-average matrix with the reference's
    adaptive bin edges: start = floor(i·in/out), end = ceil((i+1)·in/out).
    Makes adaptive pooling two separable matmuls (MXU-shaped)."""
    i = jnp.arange(out_len)
    start = jnp.floor(i * in_len / out_len).astype(jnp.int32)
    end = jnp.ceil((i + 1) * in_len / out_len).astype(jnp.int32)
    j = jnp.arange(in_len)
    mask = (j[None, :] >= start[:, None]) & (j[None, :] < end[:, None])
    m = mask.astype(jnp.float32)
    return m / jnp.maximum(m.sum(axis=1, keepdims=True), 1.0)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    x = _v(x)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    if data_format == "NHWC":
        return jnp.moveaxis(
            adaptive_avg_pool2d(jnp.moveaxis(x, -1, 1), output_size), 1, -1)
    h, w = x.shape[2], x.shape[3]
    if h % output_size[0] == 0 and w % output_size[1] == 0:
        k = (h // output_size[0], w // output_size[1])
        return avg_pool2d(x, k, k, 0, data_format)
    my = _adaptive_avg_matrix(output_size[0], h)
    mx = _adaptive_avg_matrix(output_size[1], w)
    return jnp.einsum("Oh,nchw,Pw->ncOP", my, x, mx).astype(x.dtype)


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------
def one_hot(x, num_classes, dtype=jnp.float32):
    return jax.nn.one_hot(_v(x), num_classes, dtype=dtype)


def pad(x, pad_width, mode="constant", value=0.0):
    x = _v(x)
    if isinstance(pad_width, (list, tuple)) and pad_width and isinstance(
        pad_width[0], int
    ):
        # paddle/torch flat style: first pair pads the LAST dim, second pair
        # the second-to-last, etc.
        pairs = list(zip(pad_width[0::2], pad_width[1::2]))
        full = [(0, 0)] * (x.ndim - len(pairs)) + pairs[::-1]
    else:
        full = pad_width
    if mode == "constant":
        return jnp.pad(x, full, constant_values=value)
    return jnp.pad(x, full, mode=mode)


def normalize(x, p=2, axis=-1, epsilon=1e-12):
    x = _v(x)
    norm = jnp.linalg.norm(x, ord=p, axis=axis, keepdims=True)
    return x / jnp.maximum(norm, epsilon)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    """Weight layout [out_c, in_c/groups, k] (paddle convention)."""
    x, weight = _v(x), _v(weight)
    if isinstance(stride, int):
        stride = (stride,)
    if isinstance(dilation, int):
        dilation = (dilation,)
    if isinstance(padding, int):
        padding = [(padding, padding)]
    elif isinstance(padding, str):
        padding = padding.upper()
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCH", "OIH", "NCH") if data_format == "NCL" else
        ("NHC", "OIH", "NHC"),
    )
    y = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.bfloat16
        else None,
    ).astype(x.dtype)
    if bias is not None:
        shape = (1, -1, 1) if data_format == "NCL" else (1, 1, -1)
        y = y + _v(bias).reshape(shape)
    return y


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    """Weight layout [out_c, in_c/groups, kd, kh, kw]."""
    x, weight = _v(x), _v(weight)
    if isinstance(stride, int):
        stride = (stride,) * 3
    if isinstance(dilation, int):
        dilation = (dilation,) * 3
    if isinstance(padding, int):
        padding = [(padding, padding)] * 3
    elif isinstance(padding, str):
        padding = padding.upper()
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCDHW", "OIDHW", "NCDHW") if data_format == "NCDHW" else
        ("NDHWC", "OIDHW", "NDHWC"),
    )
    y = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.bfloat16
        else None,
    ).astype(x.dtype)
    if bias is not None:
        shape = (1, -1, 1, 1, 1) if data_format == "NCDHW" \
            else (1, 1, 1, 1, -1)
        y = y + _v(bias).reshape(shape)
    return y


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW"):
    """Gradient/fractionally-strided conv (parity: F.conv2d_transpose).
    Weight layout [in_c, out_c/groups, kh, kw] (paddle convention).
    Implemented as conv_general_dilated with lhs_dilation=stride — the
    exact transpose of the forward conv, which XLA maps to the MXU the
    same way."""
    x, weight = _v(x), _v(weight)
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(output_padding, int):
        output_padding = (output_padding, output_padding)
    kh, kw = weight.shape[-2:]
    # transpose-conv padding: k - 1 - p on each side (+output_padding low)
    pads = []
    for (k, p, op, d) in ((kh, padding[0], output_padding[0], dilation[0]),
                          (kw, padding[1], output_padding[1], dilation[1])):
        eff_k = (k - 1) * d + 1
        pads.append((eff_k - 1 - p, eff_k - 1 - p + op))
    # weight [in, out/groups, kh, kw] → flip taps, swap to [out, in/groups]
    w = jnp.flip(weight, axis=(-2, -1))
    if groups == 1:
        w = jnp.swapaxes(w, 0, 1)  # [out, in, kh, kw]
    else:
        i, og, khw = weight.shape[0], weight.shape[1], weight.shape[2:]
        w = w.reshape(groups, i // groups, og, *khw)
        w = jnp.swapaxes(w, 1, 2).reshape(groups * og, i // groups, *khw)
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else
        ("NHWC", "OIHW", "NHWC"),
    )
    y = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pads, lhs_dilation=stride,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.bfloat16
        else None,
    ).astype(x.dtype)
    if bias is not None:
        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        y = y + _v(bias).reshape(shape)
    return y


def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    if data_format == "NCHW":
        b, c, h, w = x.shape
        x = x.reshape(b, c // (r * r), r, r, h, w)
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return x.reshape(b, c // (r * r), h * r, w * r)
    b, h, w, c = x.shape
    x = x.reshape(b, h, w, r, r, c // (r * r))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(b, h * r, w * r, c // (r * r))


def cosine_similarity(x1, x2, axis=-1, eps=1e-8):
    x1, x2 = _v(x1), _v(x2)
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


# ---------------------------------------------------------------------------
# CTC loss
# ---------------------------------------------------------------------------
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Connectionist Temporal Classification loss.

    Parity: paddle.nn.functional.ctc_loss (reference: the warpctc op,
    paddle/phi/kernels/impl/warpctc_kernel_impl.h, built from the vendored
    third_party warpctc — SURVEY §2.3). ``log_probs`` are UNNORMALIZED
    logits of shape [max_time, batch, num_classes]; softmax is applied
    internally, matching warpctc.

    TPU design: warpctc's hand-scheduled CUDA alpha/beta kernels become a
    single ``lax.scan`` over time of the log-semiring alpha recursion on
    the extended (blank-interleaved) label sequence — static shapes,
    batch-vectorized, masked for variable time/label lengths. The backward
    pass is jax autodiff through the scan, which reproduces the classic
    beta-recursion gradient without a hand-written kernel.
    """
    lp = jax.nn.log_softmax(_f32up(_v(log_probs)), axis=-1)
    labels = _v(labels)
    input_lengths = jnp.asarray(input_lengths, jnp.int32)
    label_lengths = jnp.asarray(label_lengths, jnp.int32)
    T, B, C = lp.shape
    L = labels.shape[1]
    S = 2 * L + 1
    neg_inf = jnp.asarray(-1e30, lp.dtype)

    # extended sequence: [blank, l0, blank, l1, ..., blank]
    s_idx = jnp.arange(S)
    lab_pos = jnp.clip((s_idx - 1) // 2, 0, L - 1)
    is_label = (s_idx % 2) == 1
    ext = jnp.where(is_label[None, :], labels[:, lab_pos], blank)  # [B, S]

    # skip transition s-2 -> s allowed iff ext[s] is a label differing
    # from ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=blank)[:, :S]
    skip_ok = is_label[None, :] & (ext != ext_m2) & (s_idx[None, :] >= 2)

    # per-step emission log-probs for every extended position: [T, B, S]
    emit = jnp.take_along_axis(
        lp, jnp.broadcast_to(ext[None], (T, B, S)), axis=2
    )

    alpha0 = jnp.full((B, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(emit[0, :, 0])
    if S > 1:
        # first label only reachable if the sequence is non-empty
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(label_lengths > 0, emit[0, :, 1], neg_inf)
        )

    def _shift(a, k):
        return jnp.pad(a, ((0, 0), (k, 0)), constant_values=neg_inf)[:, :S]

    def step(alpha, xs):
        emit_t, t = xs
        a1 = alpha
        a2 = _shift(alpha, 1)
        a3 = jnp.where(skip_ok, _shift(alpha, 2), neg_inf)
        stacked = jnp.stack([a1, a2, a3])
        m = jnp.max(stacked, axis=0)
        new = m + jnp.log(
            jnp.sum(jnp.exp(stacked - m[None]), axis=0)
        ) + emit_t
        # freeze alpha once past each sequence's input length
        alpha = jnp.where((t < input_lengths)[:, None], new, alpha)
        return alpha, None

    alpha, _ = lax.scan(step, alpha0, (emit[1:], jnp.arange(1, T)))

    last = 2 * label_lengths  # final blank position in the extended seq
    a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
    a_prev = jnp.where(
        label_lengths > 0,
        jnp.take_along_axis(
            alpha, jnp.maximum(last - 1, 0)[:, None], axis=1
        )[:, 0],
        neg_inf,
    )
    m = jnp.maximum(a_last, a_prev)
    ll = m + jnp.log(jnp.exp(a_last - m) + jnp.exp(a_prev - m))
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(input_lengths, 1).astype(loss.dtype)
    if reduction == "mean":
        # paddle: divide each loss by its label length, then mean
        return jnp.mean(
            loss / jnp.maximum(label_lengths, 1).astype(loss.dtype)
        )
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


# ---------------------------------------------------------------------------
# interpolate / grid_sample
# ---------------------------------------------------------------------------
def _resize_src_index(out_len, in_len, align_corners, align_mode=0):
    i = jnp.arange(out_len, dtype=jnp.float32)
    if align_corners:
        if out_len == 1:
            return jnp.zeros((1,), jnp.float32)
        return i * (in_len - 1) / (out_len - 1)
    if align_mode == 1:   # paddle asymmetric mode: src = i·in/out
        return jnp.clip(i * in_len / out_len, 0.0, in_len - 1.0)
    return jnp.clip((i + 0.5) * in_len / out_len - 0.5, 0.0,
                    in_len - 1.0)


def _cubic_weights(out_len, in_len, align_corners, a=-0.75):
    """Separable cubic-convolution matrix [out, in] with the torch/paddle
    kernel (a = -0.75) and border-replicated taps."""
    if align_corners:
        src = _resize_src_index(out_len, in_len, True)
    else:
        # raw half-pixel coordinate (unclipped — edge taps replicate via
        # the index clamp below)
        i = jnp.arange(out_len, dtype=jnp.float32)
        src = (i + 0.5) * in_len / out_len - 0.5
    base = jnp.floor(src).astype(jnp.int32)
    t = src - base

    def k(x):
        ax = jnp.abs(x)
        w1 = (a + 2) * ax ** 3 - (a + 3) * ax ** 2 + 1
        w2 = a * ax ** 3 - 5 * a * ax ** 2 + 8 * a * ax - 4 * a
        return jnp.where(ax <= 1, w1, jnp.where(ax < 2, w2, 0.0))

    m = jnp.zeros((out_len, in_len))
    rows = jnp.arange(out_len)
    for off in (-1, 0, 1, 2):
        idx = jnp.clip(base + off, 0, in_len - 1)
        m = m.at[rows, idx].add(k(t - off))
    return m


def _lin_weights(out_len, in_len, align_corners, align_mode=0):
    """Separable 1-D interpolation matrix [out_len, in_len]."""
    src = _resize_src_index(out_len, in_len, align_corners, align_mode)
    lo = jnp.floor(src).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, in_len - 1)
    w_hi = src - lo
    m = jnp.zeros((out_len, in_len))
    m = m.at[jnp.arange(out_len), lo].add(1.0 - w_hi)
    m = m.at[jnp.arange(out_len), hi].add(w_hi)
    return m


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW"):
    """Parity: paddle.nn.functional.interpolate — 3-D NCW (linear /
    nearest), 4-D NCHW/NHWC (nearest / bilinear / bicubic / area), 5-D
    NCDHW (trilinear / nearest).

    TPU design: linear modes are separable [out, in] matmuls (MXU ops,
    trivially fused by XLA) rather than gathers; nearest is a pure
    gather; area is adaptive average pooling.
    """
    x = _v(x)
    if data_format in ("NWC", "NHWC", "NDHWC"):
        fmt = {"NWC": "NCW", "NHWC": "NCHW", "NDHWC": "NCDHW"}
        return jnp.moveaxis(
            interpolate(jnp.moveaxis(x, -1, 1), size, scale_factor, mode,
                        align_corners, align_mode, fmt[data_format]),
            1, -1)
    if x.ndim == 3:
        n, c, w = x.shape
        if size is not None:
            ow = size if isinstance(size, int) else tuple(size)[0]
        else:
            sf = scale_factor if not isinstance(
                scale_factor, (tuple, list)) else scale_factor[0]
            ow = int(w * sf)
        if mode == "nearest":
            ix = jnp.minimum(jnp.arange(ow) * w // ow, w - 1)
            return x[:, :, ix]
        if mode == "linear":
            mx = _lin_weights(ow, w, align_corners, align_mode)
            return jnp.einsum("Ow,ncw->ncO", mx, x).astype(x.dtype)
        raise ValueError(f"interpolate 3-D: unknown mode {mode!r}")
    if x.ndim == 5:
        n, c, d, h, w = x.shape
        if size is not None:
            od, oh, ow = (size,) * 3 if isinstance(size, int) \
                else tuple(size)
        else:
            sf = (scale_factor,) * 3 if not isinstance(
                scale_factor, (tuple, list)) else scale_factor
            od, oh, ow = int(d * sf[0]), int(h * sf[1]), int(w * sf[2])
        if mode == "nearest":
            iz = jnp.minimum(jnp.arange(od) * d // od, d - 1)
            iy = jnp.minimum(jnp.arange(oh) * h // oh, h - 1)
            ix = jnp.minimum(jnp.arange(ow) * w // ow, w - 1)
            return x[:, :, iz][:, :, :, iy][:, :, :, :, ix]
        if mode == "trilinear":
            mz = _lin_weights(od, d, align_corners, align_mode)
            my = _lin_weights(oh, h, align_corners, align_mode)
            mx = _lin_weights(ow, w, align_corners, align_mode)
            return jnp.einsum(
                "Dd,Hh,Ww,ncdhw->ncDHW", mz, my, mx, x
            ).astype(x.dtype)
        raise ValueError(f"interpolate 5-D: unknown mode {mode!r}")
    n, c, h, w = x.shape
    if size is not None:
        oh, ow = (size, size) if isinstance(size, int) else tuple(size)
    else:
        sf = (scale_factor, scale_factor) if not isinstance(
            scale_factor, (tuple, list)) else scale_factor
        oh, ow = int(h * sf[0]), int(w * sf[1])
    if mode == "nearest":
        # paddle/torch nearest: floor(i * in/out)
        iy = jnp.minimum((jnp.arange(oh) * h // oh), h - 1)
        ix = jnp.minimum((jnp.arange(ow) * w // ow), w - 1)
        return x[:, :, iy][:, :, :, ix]
    if mode == "bilinear":
        my = _lin_weights(oh, h, align_corners, align_mode)
        mx = _lin_weights(ow, w, align_corners, align_mode)
        return jnp.einsum("Oh,nchw,Pw->ncOP", my, x, mx).astype(x.dtype)
    if mode == "bicubic":
        my = _cubic_weights(oh, h, align_corners)
        mx = _cubic_weights(ow, w, align_corners)
        return jnp.einsum("Oh,nchw,Pw->ncOP", my, x, mx).astype(x.dtype)
    if mode == "area":
        return adaptive_avg_pool2d(x, (oh, ow))
    raise ValueError(f"interpolate: unknown mode {mode!r}")


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW"):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


def _unnormalize_coord(g, size, align_corners):
    if align_corners:
        return (g + 1.0) * 0.5 * (size - 1)
    return ((g + 1.0) * size - 1.0) * 0.5


def _reflect_coord(p, size, align_corners):
    if align_corners:
        span = 2.0 * (size - 1)
        if size == 1:
            return jnp.zeros_like(p)
        p = jnp.abs(jnp.mod(p, span))
        return jnp.where(p > size - 1, span - p, p)
    span = 2.0 * size
    p = jnp.mod(p + 0.5, span)
    p = jnp.abs(p)
    p = jnp.where(p > size, span - p, p)
    return jnp.clip(p - 0.5, 0.0, size - 1.0)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """Parity: paddle.nn.functional.grid_sample. x [N, C, H, W]; grid
    [N, Hg, Wg, 2] with normalized (x, y) in [-1, 1]. One batched
    bilinear gather — autodiff replaces the reference's atomic-add
    backward kernel."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample: unknown mode {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(
            f"grid_sample: unknown padding_mode {padding_mode!r}")
    x = _v(x)
    grid = _v(grid)
    n, c, h, w = x.shape
    gx = _unnormalize_coord(_f32up(grid[..., 0]), w, align_corners)
    gy = _unnormalize_coord(_f32up(grid[..., 1]), h, align_corners)
    if padding_mode == "reflection":
        gx = _reflect_coord(gx, w, align_corners)
        gy = _reflect_coord(gy, h, align_corners)

    def sample_one(feat, yy, xx):
        if padding_mode == "zeros":
            ring = jnp.pad(feat, ((0, 0), (1, 1), (1, 1)))
            far = (yy < -1.0) | (yy > h) | (xx < -1.0) | (xx > w)
            yy2 = jnp.clip(yy + 1.0, 0.0, h + 1.0)
            xx2 = jnp.clip(xx + 1.0, 0.0, w + 1.0)
            if mode == "nearest":
                iy = jnp.round(yy2).astype(jnp.int32)
                ix = jnp.round(xx2).astype(jnp.int32)
                vals = ring[:, iy, ix]
            else:
                vals = _bilerp(ring, yy2, xx2)
            return jnp.where(far[None], 0.0, vals)
        yy2 = jnp.clip(yy, 0.0, h - 1.0)
        xx2 = jnp.clip(xx, 0.0, w - 1.0)
        if mode == "nearest":
            return feat[:, jnp.round(yy2).astype(jnp.int32),
                        jnp.round(xx2).astype(jnp.int32)]
        return _bilerp(feat, yy2, xx2)

    return jax.vmap(sample_one)(x, gy, gx).astype(x.dtype)


def _bilerp(feat, y, x):
    """feat [C, H, W]; y/x same-shaped float grids → [C, *grid]."""
    H, W = feat.shape[-2:]
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    wy1 = y - y0
    wx1 = x - x0
    return (feat[:, y0, x0] * ((1 - wy1) * (1 - wx1))
            + feat[:, y0, x1] * ((1 - wy1) * wx1)
            + feat[:, y1, x0] * (wy1 * (1 - wx1))
            + feat[:, y1, x1] * (wy1 * wx1))


# ---------------------------------------------------------------------------
# functional loss forms (parity: python/paddle/nn/functional/loss.py);
# the corresponding nn.layer.loss classes delegate here
# ---------------------------------------------------------------------------
def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def kl_div(input, label, reduction="mean"):  # noqa: A002
    """input is LOG-probabilities (paddle convention)."""
    x, t = _v(input), _v(label)
    loss = t * (jnp.log(jnp.clip(t, 1e-30)) - x)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    return _reduce_loss(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0,
                        reduction="mean"):  # noqa: A002
    loss = jnp.maximum(
        0.0, -_v(label) * (_v(input) - _v(other)) + margin)
    return _reduce_loss(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):  # noqa: A002
    d = jnp.abs(_v(input) - _v(label))
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce_loss(loss, reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False,
                        reduction="mean"):  # noqa: A002
    def dist(a, b):
        return jnp.power(
            jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p), axis=-1),
            1.0 / p)

    a, pos, neg = _v(input), _v(positive), _v(negative)
    d_pos = dist(a, pos)
    d_neg = dist(a, neg)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(pos, neg))
    return _reduce_loss(jnp.maximum(0.0, d_pos - d_neg + margin),
                        reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean"):
    x1, x2 = _v(input1), _v(input2)
    if x1.ndim == 1:      # paddle accepts a single [M] pair
        x1, x2 = x1[None], x2[None]
    cos = cosine_similarity(x1, x2, axis=1)
    loss = jnp.where(_v(label) > 0, 1.0 - cos,
                     jnp.maximum(0.0, cos - margin))
    return _reduce_loss(loss, reduction)


def soft_margin_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce_loss(jax.nn.softplus(-_v(label) * _v(input)),
                        reduction)


def hinge_embedding_loss(input, label, margin=1.0,
                         reduction="mean"):  # noqa: A002
    x = _v(input)
    loss = jnp.where(_v(label) > 0, x, jnp.maximum(0.0, margin - x))
    return _reduce_loss(loss, reduction)


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean"):  # noqa: A002
    x, t = _v(input), _v(label)
    if log_input:
        loss = jnp.exp(x) - t * x
    else:
        loss = x - t * jnp.log(x + epsilon)
    if full:
        stirling = (t * jnp.log(t) - t
                    + 0.5 * jnp.log(2.0 * jnp.pi * t))
        loss = loss + jnp.where(t > 1, stirling, 0.0)
    return _reduce_loss(loss, reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):  # noqa: A002
    var = jnp.maximum(_v(variance), epsilon)
    loss = 0.5 * (jnp.log(var) + jnp.square(_v(input) - _v(label)) / var)
    if full:
        loss = loss + 0.5 * jnp.log(jnp.asarray(2.0 * jnp.pi))
    return _reduce_loss(loss, reduction)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean"):  # noqa: A002
    x, t = _v(input), _v(label)
    loss = -(t * jax.nn.log_sigmoid(x)
             + (1 - t) * jax.nn.log_sigmoid(-x))
    if weight is not None:
        loss = loss * _v(weight)
    return _reduce_loss(jnp.mean(loss, axis=-1), reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0, reduction="sum"):
    """Parity: paddle.nn.functional.sigmoid_focal_loss (RetinaNet)."""
    x, t = _f32up(_v(logit)), _v(label).astype(jnp.float32)
    p = jax.nn.sigmoid(x)
    ce = -(t * jax.nn.log_sigmoid(x) + (1 - t) * jax.nn.log_sigmoid(-x))
    p_t = p * t + (1 - p) * (1 - t)
    a_t = alpha * t + (1 - alpha) * (1 - t)
    loss = a_t * jnp.power(1 - p_t, gamma) * ce
    if normalizer is not None:
        loss = loss / _v(normalizer)
    return _reduce_loss(loss, reduction)


def dice_loss(input, label, epsilon=1e-5):  # noqa: A002
    """Parity: paddle.nn.functional.dice_loss — input [N, ..., C]
    probabilities, label [N, ..., 1] class ids."""
    x = _v(input)
    t = jax.nn.one_hot(jnp.squeeze(_v(label), -1), x.shape[-1],
                       dtype=x.dtype)
    reduce_dims = tuple(range(1, x.ndim))
    inter = jnp.sum(x * t, axis=reduce_dims)
    union = jnp.sum(x, axis=reduce_dims) + jnp.sum(t, axis=reduce_dims)
    return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))


def log_loss(input, label, epsilon=1e-4):  # noqa: A002
    """Parity: paddle.nn.functional.log_loss (probability input)."""
    x, t = _v(input), _v(label)
    return -(t * jnp.log(x + epsilon)
             + (1 - t) * jnp.log(1 - x + epsilon))


def square_error_cost(input, label):  # noqa: A002
    return jnp.square(_v(input) - _v(label))


# ---------------------------------------------------------------------------
# remaining activation functional forms (parity: paddle.nn.functional —
# the activation Layer classes keep their own thin forwards; these are
# the F.* spellings)
# ---------------------------------------------------------------------------
def log_sigmoid(x):
    return jax.nn.log_sigmoid(_v(x))


def softsign(x):
    return jax.nn.soft_sign(_v(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    # jax.nn.elu guards expm1 against overflow in the untaken branch
    # (bare where leaks NaN grads at large positive x)
    return scale * jax.nn.elu(_v(x), alpha)


def celu(x, alpha=1.0):
    return jax.nn.celu(_v(x), alpha)


def hardshrink(x, threshold=0.5):
    x = _v(x)
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def softshrink(x, threshold=0.5):
    x = _v(x)
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


def tanhshrink(x):
    x = _v(x)
    return x - jnp.tanh(x)


def hardtanh(x, min=-1.0, max=1.0):  # noqa: A002
    return jnp.clip(_v(x), min, max)


def thresholded_relu(x, threshold=1.0):
    x = _v(x)
    return jnp.where(x > threshold, x, 0.0)


def prelu(x, weight):
    """weight: scalar-shaped [1] or per-channel [C] (paddle NCHW
    channel-1 convention for >2-D inputs)."""
    x, w = _v(x), _v(weight)
    if w.size > 1 and x.ndim > 2:
        w = w.reshape((1, -1) + (1,) * (x.ndim - 2))
    return jnp.where(x > 0, x, w * x)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True,
          rng_key=None):
    """Randomized leaky ReLU: U[lower, upper] slope in training, the
    midpoint at inference (paddle semantics)."""
    x = _v(x)
    if not training:
        return jnp.where(x > 0, x, (lower + upper) / 2.0 * x)
    key = rng_key if rng_key is not None else \
        random_mod.next_rng_key("rrelu")
    slope = jax.random.uniform(key, x.shape, jnp.float32, lower, upper)
    return jnp.where(x > 0, x, slope.astype(x.dtype) * x)


def maxout(x, groups, axis=1):
    """Parity: paddle.nn.functional.maxout — max over ``groups``-sized
    channel blocks."""
    x = _v(x)
    axis = axis % x.ndim          # negative axis: normalize BEFORE the
    c = x.shape[axis]             # slice-splice below
    if c % groups:
        raise ValueError(f"maxout: channels {c} not divisible by "
                         f"groups {groups}")
    shape = list(x.shape)
    shape[axis: axis + 1] = [c // groups, groups]
    return jnp.max(x.reshape(shape), axis=axis + 1)
