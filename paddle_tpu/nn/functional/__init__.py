"""Functional ops (parity: paddle.nn.functional).

Thin, jit-friendly wrappers over jax.numpy/lax, organized by domain in
the reference's own module layout (python/paddle/nn/functional/
{activation,common,conv,pooling,loss,norm,vision,input,
flash_attention}.py). Where the reference routes through hand-written
CUDA kernels (paddle/phi/kernels/gpu/, paddle/phi/kernels/fusion/), XLA
fusion covers the same ground on TPU; the genuinely hot fused paths
(flash attention, rope/rmsnorm at long seq, paged decode) live in
paddle_tpu.kernels as Pallas implementations and are dispatched from
here when available.
"""

from .activation import (  # noqa: F401
    celu,
    elu,
    gelu,
    glu,
    hardshrink,
    hardsigmoid,
    hardswish,
    hardtanh,
    leaky_relu,
    log_sigmoid,
    log_softmax,
    maxout,
    mish,
    prelu,
    relu,
    relu6,
    rrelu,
    selu,
    sigmoid,
    silu,
    softmax,
    softplus,
    softshrink,
    softsign,
    swiglu,
    swish,
    tanh,
    tanhshrink,
    thresholded_relu,
)
from .common import (  # noqa: F401
    _f32up,
    _v,
    alpha_dropout,
    bilinear,
    channel_shuffle,
    cosine_similarity,
    dropout,
    dropout2d,
    dropout3d,
    fold,
    gumbel_softmax,
    label_smooth,
    pairwise_distance,
    sequence_mask,
    temporal_shift,
    interpolate,
    linear,
    pad,
    unfold,
    upsample,
    zeropad2d,
)
from .conv import (  # noqa: F401
    conv1d,
    conv1d_transpose,
    conv2d,
    conv2d_transpose,
    conv3d,
    conv3d_transpose,
)
from .flash_attention import (  # noqa: F401
    flash_attention,
    scaled_dot_product_attention,
)
from .input import embedding, one_hot  # noqa: F401
from .loss import (  # noqa: F401
    binary_cross_entropy,
    binary_cross_entropy_with_logits,
    cosine_embedding_loss,
    cross_entropy,
    ctc_loss,
    rnnt_loss,
    dice_loss,
    gaussian_nll_loss,
    hinge_embedding_loss,
    kl_div,
    l1_loss,
    log_loss,
    margin_ranking_loss,
    mse_loss,
    multi_label_soft_margin_loss,
    nll_loss,
    poisson_nll_loss,
    sigmoid_focal_loss,
    smooth_l1_loss,
    soft_margin_loss,
    square_error_cost,
    triplet_margin_loss,
)
from .norm import (  # noqa: F401
    group_norm,
    layer_norm,
    local_response_norm,
    normalize,
    rms_norm,
)
from .pooling import (  # noqa: F401
    adaptive_avg_pool1d,
    adaptive_max_pool1d,
    avg_pool1d,
    max_pool1d,
    adaptive_avg_pool2d,
    adaptive_avg_pool3d,
    adaptive_max_pool2d,
    avg_pool2d,
    avg_pool3d,
    max_pool2d,
    max_pool3d,
)
from .vision import (  # noqa: F401
    _bilerp,
    grid_sample,
    affine_grid,
    pixel_shuffle,
    pixel_unshuffle,
)
