"""Input encodings (parity: python/paddle/nn/functional/input.py — one_hot, embedding)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .common import _v


def one_hot(x, num_classes, dtype=jnp.float32):
    return jax.nn.one_hot(_v(x), num_classes, dtype=dtype)


def embedding(x, weight, padding_idx=None):
    x, weight = _v(x), _v(weight)
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros((), out.dtype), out)
    return out
