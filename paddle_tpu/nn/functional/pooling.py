"""Pooling functional forms (parity: python/paddle/nn/functional/pooling.py)."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .. import layout
from .common import _v


def max_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW"):
    x = _v(x)
    data_format = layout.resolve(data_format)
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    if data_format == "NCHW":
        window = (1, 1) + tuple(kernel_size)
        strides = (1, 1) + tuple(stride)
        pads = [(0, 0), (0, 0)] + list(padding)
    else:
        window = (1,) + tuple(kernel_size) + (1,)
        strides = (1,) + tuple(stride) + (1,)
        pads = [(0, 0)] + list(padding) + [(0, 0)]
    return lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max, window, strides, pads,
    )


def avg_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW"):
    x = _v(x)
    data_format = layout.resolve(data_format)
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    if data_format == "NCHW":
        window = (1, 1) + tuple(kernel_size)
        strides = (1, 1) + tuple(stride)
        pads = [(0, 0), (0, 0)] + list(padding)
    else:
        window = (1,) + tuple(kernel_size) + (1,)
        strides = (1,) + tuple(stride) + (1,)
        pads = [(0, 0)] + list(padding) + [(0, 0)]
    summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    counts = lax.reduce_window(
        jnp.ones_like(x), 0.0, lax.add, window, strides, pads
    )
    return summed / counts


def _adaptive_avg_matrix(out_len, in_len):
    """[out, in] row-stochastic bin-average matrix with the reference's
    adaptive bin edges: start = floor(i·in/out), end = ceil((i+1)·in/out).
    Makes adaptive pooling two separable matmuls (MXU-shaped)."""
    i = jnp.arange(out_len)
    start = jnp.floor(i * in_len / out_len).astype(jnp.int32)
    end = jnp.ceil((i + 1) * in_len / out_len).astype(jnp.int32)
    j = jnp.arange(in_len)
    mask = (j[None, :] >= start[:, None]) & (j[None, :] < end[:, None])
    m = mask.astype(jnp.float32)
    return m / jnp.maximum(m.sum(axis=1, keepdims=True), 1.0)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    x = _v(x)
    data_format = layout.resolve(data_format)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    if data_format == "NHWC":
        h, w = x.shape[1], x.shape[2]
        if h % output_size[0] == 0 and w % output_size[1] == 0:
            # native channels-last: window pool directly, no transposes
            k = (h // output_size[0], w // output_size[1])
            return avg_pool2d(x, k, k, 0, "NHWC")
        my = _adaptive_avg_matrix(output_size[0], h)
        mx = _adaptive_avg_matrix(output_size[1], w)
        return jnp.einsum("Oh,nhwc,Pw->nOPc", my, x, mx).astype(x.dtype)
    h, w = x.shape[2], x.shape[3]
    if h % output_size[0] == 0 and w % output_size[1] == 0:
        k = (h // output_size[0], w // output_size[1])
        return avg_pool2d(x, k, k, 0, data_format)
    my = _adaptive_avg_matrix(output_size[0], h)
    mx = _adaptive_avg_matrix(output_size[1], w)
    return jnp.einsum("Oh,nchw,Pw->ncOP", my, x, mx).astype(x.dtype)


def _pool_nd(x, nd, kernel_size, stride, padding, data_format, kind):
    """Shared N-D window pool (parity: phi pool3d/pool1d kernels —
    one lax.reduce_window per call, XLA picks the TPU schedule)."""
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size,) * nd
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride,) * nd
    if isinstance(padding, int):
        padding = [(padding, padding)] * nd
    channels_first = data_format in ("NCHW", "NCL", "NCDHW")
    if channels_first:
        window = (1, 1) + tuple(kernel_size)
        strides = (1, 1) + tuple(stride)
        pads = [(0, 0), (0, 0)] + list(padding)
    else:
        window = (1,) + tuple(kernel_size) + (1,)
        strides = (1,) + tuple(stride) + (1,)
        pads = [(0, 0)] + list(padding) + [(0, 0)]
    if kind == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, pads)
    summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    counts = lax.reduce_window(
        jnp.ones_like(x), 0.0, lax.add, window, strides, pads)
    return summed / counts


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NCDHW"):
    return _pool_nd(_v(x), 3, kernel_size, stride, padding, data_format,
                    "max")


def avg_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NCDHW"):
    return _pool_nd(_v(x), 3, kernel_size, stride, padding, data_format,
                    "avg")


def adaptive_avg_pool1d(x, output_size):
    """x [N, C, L] (parity: F.adaptive_avg_pool1d)."""
    x = _v(x)
    L = x.shape[2]
    if isinstance(output_size, (tuple, list)):
        output_size = output_size[0]
    if L % output_size == 0:
        k = L // output_size
        return _pool_nd(x, 1, (k,), (k,), 0, "NCL", "avg")
    m = _adaptive_avg_matrix(output_size, L)
    return jnp.einsum("Ol,ncl->ncO", m, x).astype(x.dtype)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    x = _v(x)
    if isinstance(output_size, int):
        output_size = (output_size,) * 3
    if data_format == "NDHWC":
        return jnp.moveaxis(
            adaptive_avg_pool3d(jnp.moveaxis(x, -1, 1), output_size),
            1, -1)
    d, h, w = x.shape[2:]
    if all(s % o == 0 for s, o in zip((d, h, w), output_size)):
        k = tuple(s // o for s, o in zip((d, h, w), output_size))
        return _pool_nd(x, 3, k, k, 0, "NCDHW", "avg")
    md = _adaptive_avg_matrix(output_size[0], d)
    mh = _adaptive_avg_matrix(output_size[1], h)
    mw = _adaptive_avg_matrix(output_size[2], w)
    y = jnp.einsum("Dd,ncdhw->ncDhw", md, x)
    y = jnp.einsum("Hh,ncDhw->ncDHw", mh, y)
    return jnp.einsum("Ww,ncDHw->ncDHW", mw, y).astype(x.dtype)


def adaptive_max_pool2d(x, output_size, return_mask=False,
                        data_format="NCHW"):
    """Adaptive max pool; reference bin edges. Non-divisible sizes use
    the segment trick: mask each bin from the padded window max.
    ``return_mask=True`` also returns the flattened h*w argmax index
    per bin (parity: F.adaptive_max_pool2d mask output)."""
    x = _v(x)
    data_format = layout.resolve(data_format)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    if data_format == "NHWC":
        # explicit transpose to channel-first: suspend scope resolution
        # or the recursion's declared NCHW re-resolves to NHWC forever
        with layout.declared_scope():
            y = adaptive_max_pool2d(jnp.moveaxis(x, -1, 1), output_size,
                                    return_mask)
        if return_mask:
            return (jnp.moveaxis(y[0], 1, -1),
                    jnp.moveaxis(y[1], 1, -1))
        return jnp.moveaxis(y, 1, -1)
    h, w = x.shape[2], x.shape[3]
    if h % output_size[0] == 0 and w % output_size[1] == 0 \
            and not return_mask:
        k = (h // output_size[0], w // output_size[1])
        return _pool_nd(x, 2, k, k, 0, "NCHW", "max")
    # general case: per-output-bin masked max via the bin matrices
    my = _adaptive_avg_matrix(output_size[0], h) > 0  # [Oh, h] bin mask
    mx = _adaptive_avg_matrix(output_size[1], w) > 0  # [Ow, w]
    neg = jnp.asarray(
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.iinfo(x.dtype).min, x.dtype)
    # [n, c, Oh, Ow, h, w] masked view is too big; do separable maxes
    # [1,1,Oh,h,1] mask against [n,c,1,h,w] -> max over h
    y1 = jnp.where(my[None, None, :, :, None], x[:, :, None, :, :], neg)
    ih = jnp.argmax(y1, axis=3)  # [n, c, Oh, w] row of each column max
    y1 = y1.max(axis=3)  # -> [n, c, Oh, w]
    # [1,1,Ow,w] mask against [n,c,Oh,1,w] -> max over w
    y2 = jnp.where(mx[None, None, None, :, :],
                   y1[:, :, :, None, :], neg)
    iw = jnp.argmax(y2, axis=-1)  # [n, c, Oh, Ow]
    out = y2.max(axis=-1)
    if not return_mask:
        return out
    # joint argmax: row index gathered at the winning column
    ih_sel = jnp.take_along_axis(ih, iw, axis=-1)  # [n, c, Oh, Ow]
    return out, ih_sel * w + iw


def max_pool1d(x, kernel_size, stride=None, padding=0,
               data_format="NCL"):
    return _pool_nd(_v(x), 1, kernel_size, stride, padding, data_format,
                    "max")


def avg_pool1d(x, kernel_size, stride=None, padding=0,
               data_format="NCL"):
    return _pool_nd(_v(x), 1, kernel_size, stride, padding, data_format,
                    "avg")


def adaptive_max_pool1d(x, output_size, return_mask=False):
    """x [N, C, L] (parity: F.adaptive_max_pool1d)."""
    x = _v(x)
    y = adaptive_max_pool2d(x[:, :, None, :], (1, output_size),
                            return_mask=return_mask)
    if return_mask:
        return y[0][:, :, 0], y[1][:, :, 0]
    return y[:, :, 0]
