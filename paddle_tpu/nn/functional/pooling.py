"""Pooling functional forms (parity: python/paddle/nn/functional/pooling.py)."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .common import _v


def max_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW"):
    x = _v(x)
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    if data_format == "NCHW":
        window = (1, 1) + tuple(kernel_size)
        strides = (1, 1) + tuple(stride)
        pads = [(0, 0), (0, 0)] + list(padding)
    else:
        window = (1,) + tuple(kernel_size) + (1,)
        strides = (1,) + tuple(stride) + (1,)
        pads = [(0, 0)] + list(padding) + [(0, 0)]
    return lax.reduce_window(
        x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max, window, strides, pads,
    )


def avg_pool2d(x, kernel_size, stride=None, padding=0, data_format="NCHW"):
    x = _v(x)
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    if data_format == "NCHW":
        window = (1, 1) + tuple(kernel_size)
        strides = (1, 1) + tuple(stride)
        pads = [(0, 0), (0, 0)] + list(padding)
    else:
        window = (1,) + tuple(kernel_size) + (1,)
        strides = (1,) + tuple(stride) + (1,)
        pads = [(0, 0)] + list(padding) + [(0, 0)]
    summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
    counts = lax.reduce_window(
        jnp.ones_like(x), 0.0, lax.add, window, strides, pads
    )
    return summed / counts


def _adaptive_avg_matrix(out_len, in_len):
    """[out, in] row-stochastic bin-average matrix with the reference's
    adaptive bin edges: start = floor(i·in/out), end = ceil((i+1)·in/out).
    Makes adaptive pooling two separable matmuls (MXU-shaped)."""
    i = jnp.arange(out_len)
    start = jnp.floor(i * in_len / out_len).astype(jnp.int32)
    end = jnp.ceil((i + 1) * in_len / out_len).astype(jnp.int32)
    j = jnp.arange(in_len)
    mask = (j[None, :] >= start[:, None]) & (j[None, :] < end[:, None])
    m = mask.astype(jnp.float32)
    return m / jnp.maximum(m.sum(axis=1, keepdims=True), 1.0)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    x = _v(x)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    if data_format == "NHWC":
        return jnp.moveaxis(
            adaptive_avg_pool2d(jnp.moveaxis(x, -1, 1), output_size), 1, -1)
    h, w = x.shape[2], x.shape[3]
    if h % output_size[0] == 0 and w % output_size[1] == 0:
        k = (h // output_size[0], w // output_size[1])
        return avg_pool2d(x, k, k, 0, data_format)
    my = _adaptive_avg_matrix(output_size[0], h)
    mx = _adaptive_avg_matrix(output_size[1], w)
    return jnp.einsum("Oh,nchw,Pw->ncOP", my, x, mx).astype(x.dtype)
