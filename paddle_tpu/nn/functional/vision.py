"""Vision functional forms (parity: python/paddle/nn/functional/vision.py — grid_sample, pixel_shuffle)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .common import _f32up, _v


def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    if data_format == "NCHW":
        b, c, h, w = x.shape
        x = x.reshape(b, c // (r * r), r, r, h, w)
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return x.reshape(b, c // (r * r), h * r, w * r)
    b, h, w, c = x.shape
    x = x.reshape(b, h, w, r, r, c // (r * r))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(b, h * r, w * r, c // (r * r))


def _unnormalize_coord(g, size, align_corners):
    if align_corners:
        return (g + 1.0) * 0.5 * (size - 1)
    return ((g + 1.0) * size - 1.0) * 0.5


def _reflect_coord(p, size, align_corners):
    if align_corners:
        span = 2.0 * (size - 1)
        if size == 1:
            return jnp.zeros_like(p)
        p = jnp.abs(jnp.mod(p, span))
        return jnp.where(p > size - 1, span - p, p)
    span = 2.0 * size
    p = jnp.mod(p + 0.5, span)
    p = jnp.abs(p)
    p = jnp.where(p > size, span - p, p)
    return jnp.clip(p - 0.5, 0.0, size - 1.0)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """Parity: paddle.nn.functional.grid_sample. x [N, C, H, W]; grid
    [N, Hg, Wg, 2] with normalized (x, y) in [-1, 1]. One batched
    bilinear gather — autodiff replaces the reference's atomic-add
    backward kernel."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample: unknown mode {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(
            f"grid_sample: unknown padding_mode {padding_mode!r}")
    x = _v(x)
    grid = _v(grid)
    n, c, h, w = x.shape
    gx = _unnormalize_coord(_f32up(grid[..., 0]), w, align_corners)
    gy = _unnormalize_coord(_f32up(grid[..., 1]), h, align_corners)
    if padding_mode == "reflection":
        gx = _reflect_coord(gx, w, align_corners)
        gy = _reflect_coord(gy, h, align_corners)

    def sample_one(feat, yy, xx):
        if padding_mode == "zeros":
            ring = jnp.pad(feat, ((0, 0), (1, 1), (1, 1)))
            far = (yy < -1.0) | (yy > h) | (xx < -1.0) | (xx > w)
            yy2 = jnp.clip(yy + 1.0, 0.0, h + 1.0)
            xx2 = jnp.clip(xx + 1.0, 0.0, w + 1.0)
            if mode == "nearest":
                iy = jnp.round(yy2).astype(jnp.int32)
                ix = jnp.round(xx2).astype(jnp.int32)
                vals = ring[:, iy, ix]
            else:
                vals = _bilerp(ring, yy2, xx2)
            return jnp.where(far[None], 0.0, vals)
        yy2 = jnp.clip(yy, 0.0, h - 1.0)
        xx2 = jnp.clip(xx, 0.0, w - 1.0)
        if mode == "nearest":
            return feat[:, jnp.round(yy2).astype(jnp.int32),
                        jnp.round(xx2).astype(jnp.int32)]
        return _bilerp(feat, yy2, xx2)

    return jax.vmap(sample_one)(x, gy, gx).astype(x.dtype)


def _bilerp(feat, y, x):
    """feat [C, H, W]; y/x same-shaped float grids → [C, *grid]."""
    H, W = feat.shape[-2:]
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(x).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    wy1 = y - y0
    wx1 = x - x0
    return (feat[:, y0, x0] * ((1 - wy1) * (1 - wx1))
            + feat[:, y0, x1] * ((1 - wy1) * wx1)
            + feat[:, y1, x0] * (wy1 * (1 - wx1))
            + feat[:, y1, x1] * (wy1 * wx1))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    """Inverse of pixel_shuffle (parity: F.pixel_unshuffle)."""
    r = downscale_factor
    if data_format == "NCHW":
        b, c, h, w = x.shape
        x = x.reshape(b, c, h // r, r, w // r, r)
        x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
        return x.reshape(b, c * r * r, h // r, w // r)
    b, h, w, c = x.shape
    x = x.reshape(b, h // r, r, w // r, r, c)
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(b, h // r, w // r, c * r * r)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Parity: F.affine_grid — [n, 2, 3] affine params -> [n, h, w, 2]
    sampling grid in [-1, 1] coords (the grid_sample companion)."""
    n, h, w = out_shape[0], out_shape[-2], out_shape[-1]
    if align_corners:
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
    else:
        ys = (jnp.arange(h) * 2 + 1) / h - 1.0
        xs = (jnp.arange(w) * 2 + 1) / w - 1.0
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [h, w, 3]
    return jnp.einsum("hwk,nik->nhwi", base, theta)
