"""Normalization functional forms (parity: python/paddle/nn/functional/norm.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .common import _f32up, _v


def layer_norm(x, normalized_shape=None, weight=None, bias=None, epsilon=1e-5):
    x = _v(x)
    # compute statistics in fp32 for bf16 inputs (parity: phi layer_norm
    # kernel accumulates in float)
    xf = _f32up(x)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + epsilon)
    y = y.astype(x.dtype)
    if weight is not None:
        y = y * _v(weight)
    if bias is not None:
        y = y + _v(bias)
    return y


def rms_norm(x, weight=None, epsilon=1e-6):
    """Parity: phi fusion rms_norm kernel."""
    x = _v(x)
    xf = _f32up(x)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = (xf * lax.rsqrt(var + epsilon)).astype(x.dtype)
    if weight is not None:
        y = y * _v(weight)
    return y


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW", activation=None):
    """GroupNorm with an optional fused activation (None | "silu").

    Under the NHWC layout policy (``nn.layout``), a declared-NCHW call
    inside a channels-last scope resolves to NHWC and dispatches to the
    fused Pallas kernel (``kernels/group_norm.py``) — one HBM pass for
    moments + normalize + affine + activation; over-budget shapes use
    the transpose-free lax reference instead."""
    x = _v(x)
    if x.ndim == 4:
        from .. import layout

        data_format = layout.resolve(data_format)
    if data_format == "NHWC" and x.ndim == 4:
        from ... import flags
        from ...kernels import group_norm as gn

        w = _v(weight) if weight is not None else None
        b = _v(bias) if bias is not None else None
        c = x.shape[-1]
        if flags.flag("fused_group_norm") and \
                gn.supports_fused(x.shape, num_groups):
            gamma = w if w is not None else jnp.ones((c,), jnp.float32)
            beta = b if b is not None else jnp.zeros((c,), jnp.float32)
            return gn.fused_group_norm(x, gamma, beta, num_groups,
                                       epsilon, activation)
        return gn.group_norm_reference(x, w, b, num_groups, epsilon,
                                       activation)
    if data_format == "NHWC":
        # non-4D channels-last: normalize channels-first, move back
        y = group_norm(jnp.moveaxis(x, -1, 1), num_groups, weight, bias,
                       epsilon, "NCHW", activation)
        return jnp.moveaxis(y, 1, -1)
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    g = num_groups
    xf = _f32up(x).reshape(n, g, c // g, *spatial)
    axes = tuple(range(2, xf.ndim))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = ((xf - mean) * lax.rsqrt(var + epsilon)).reshape(n, c, *spatial).astype(x.dtype)
    if weight is not None:
        y = y * _v(weight).reshape(1, c, *([1] * len(spatial)))
    if bias is not None:
        y = y + _v(bias).reshape(1, c, *([1] * len(spatial)))
    if activation == "silu":
        y = y * jax.nn.sigmoid(y.astype(jnp.float32)).astype(y.dtype)
    elif activation is not None:
        raise ValueError(f"group_norm: unknown activation {activation!r}")
    return y


def normalize(x, p=2, axis=-1, epsilon=1e-12):
    x = _v(x)
    norm = jnp.linalg.norm(x, ord=p, axis=axis, keepdims=True)
    return x / jnp.maximum(norm, epsilon)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    """Parity: F.local_response_norm (AlexNet LRN) — torch/paddle
    semantics: divide by (k + alpha * mean-of-squares over a size-wide
    channel window)^beta."""
    x = _v(x)
    if data_format.endswith("C"):
        x = jnp.moveaxis(x, -1, 1)
    sq = _f32up(x) * _f32up(x)
    pads = [(0, 0), (size // 2, (size - 1) // 2)] + \
        [(0, 0)] * (x.ndim - 2)
    window = (1, size) + (1,) * (x.ndim - 2)
    summed = lax.reduce_window(sq, 0.0, lax.add, window,
                               (1,) * x.ndim, pads)
    y = (x / jnp.power(k + alpha * summed / size, beta)).astype(x.dtype)
    if data_format.endswith("C"):
        y = jnp.moveaxis(y, 1, -1)
    return y
