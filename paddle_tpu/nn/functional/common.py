"""Shared helpers + common functional ops (parity: python/paddle/nn/functional/common.py — linear, dropout, pad,
interpolate/upsample, cosine_similarity)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.parameter import Parameter
from ...core import random as random_mod


def _v(x):
    return x.value if isinstance(x, Parameter) else x

def _f32up(x):
    """Upcast to AT LEAST float32 for stable statistics — never downcast
    (fp64 inputs, e.g. the OpTest finite-difference harness, stay fp64)."""
    return x.astype(jnp.promote_types(x.dtype, jnp.float32))


def linear(x, weight, bias=None):
    """y = x @ W (+ b). Weight layout [in_features, out_features] (paddle
    convention, phi kernel matmul_kernel)."""
    x, weight = _v(x), _v(weight)
    y = jnp.matmul(x, weight)
    if bias is not None:
        y = y + _v(bias)
    return y


def dropout(x, p=0.5, training=True, mode="upscale_in_train", rng_key=None):
    x = _v(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x * (1.0 - p)
        return x
    if p == 1.0:
        return jnp.zeros_like(x)
    key = rng_key if rng_key is not None else random_mod.next_rng_key("dropout")
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(mask, x / keep, jnp.zeros((), x.dtype)).astype(x.dtype)
    return jnp.where(mask, x, jnp.zeros((), x.dtype))


def pad(x, pad_width, mode="constant", value=0.0):
    x = _v(x)
    if isinstance(pad_width, (list, tuple)) and pad_width and isinstance(
        pad_width[0], int
    ):
        # paddle/torch flat style: first pair pads the LAST dim, second pair
        # the second-to-last, etc.
        pairs = list(zip(pad_width[0::2], pad_width[1::2]))
        full = [(0, 0)] * (x.ndim - len(pairs)) + pairs[::-1]
    else:
        full = pad_width
    if mode == "constant":
        return jnp.pad(x, full, constant_values=value)
    return jnp.pad(x, full, mode=mode)


def cosine_similarity(x1, x2, axis=-1, eps=1e-8):
    x1, x2 = _v(x1), _v(x2)
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


def _resize_src_index(out_len, in_len, align_corners, align_mode=0):
    i = jnp.arange(out_len, dtype=jnp.float32)
    if align_corners:
        if out_len == 1:
            return jnp.zeros((1,), jnp.float32)
        return i * (in_len - 1) / (out_len - 1)
    if align_mode == 1:   # paddle asymmetric mode: src = i·in/out
        return jnp.clip(i * in_len / out_len, 0.0, in_len - 1.0)
    return jnp.clip((i + 0.5) * in_len / out_len - 0.5, 0.0,
                    in_len - 1.0)


def _cubic_weights(out_len, in_len, align_corners, a=-0.75):
    """Separable cubic-convolution matrix [out, in] with the torch/paddle
    kernel (a = -0.75) and border-replicated taps."""
    if align_corners:
        src = _resize_src_index(out_len, in_len, True)
    else:
        # raw half-pixel coordinate (unclipped — edge taps replicate via
        # the index clamp below)
        i = jnp.arange(out_len, dtype=jnp.float32)
        src = (i + 0.5) * in_len / out_len - 0.5
    base = jnp.floor(src).astype(jnp.int32)
    t = src - base

    def k(x):
        ax = jnp.abs(x)
        w1 = (a + 2) * ax ** 3 - (a + 3) * ax ** 2 + 1
        w2 = a * ax ** 3 - 5 * a * ax ** 2 + 8 * a * ax - 4 * a
        return jnp.where(ax <= 1, w1, jnp.where(ax < 2, w2, 0.0))

    m = jnp.zeros((out_len, in_len))
    rows = jnp.arange(out_len)
    for off in (-1, 0, 1, 2):
        idx = jnp.clip(base + off, 0, in_len - 1)
        m = m.at[rows, idx].add(k(t - off))
    return m


def _lin_weights(out_len, in_len, align_corners, align_mode=0):
    """Separable 1-D interpolation matrix [out_len, in_len]."""
    src = _resize_src_index(out_len, in_len, align_corners, align_mode)
    lo = jnp.floor(src).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, in_len - 1)
    w_hi = src - lo
    m = jnp.zeros((out_len, in_len))
    m = m.at[jnp.arange(out_len), lo].add(1.0 - w_hi)
    m = m.at[jnp.arange(out_len), hi].add(w_hi)
    return m


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW"):
    """Parity: paddle.nn.functional.interpolate — 3-D NCW (linear /
    nearest), 4-D NCHW/NHWC (nearest / bilinear / bicubic / area), 5-D
    NCDHW (trilinear / nearest).

    TPU design: linear modes are separable [out, in] matmuls (MXU ops,
    trivially fused by XLA) rather than gathers; nearest is a pure
    gather; area is adaptive average pooling.
    """
    x = _v(x)
    if x.ndim == 4:
        from .. import layout

        data_format = layout.resolve(data_format)
    if data_format == "NHWC" and x.ndim == 4 and mode == "nearest":
        # native channels-last nearest (the UNet upsampler under the
        # NHWC layout policy): index H/W directly, no transposes
        n, h, w, c = x.shape
        if size is not None:
            oh, ow = (size, size) if isinstance(size, int) else tuple(size)
        else:
            sf = (scale_factor, scale_factor) if not isinstance(
                scale_factor, (tuple, list)) else scale_factor
            oh, ow = int(h * sf[0]), int(w * sf[1])
        iy = jnp.minimum(jnp.arange(oh) * h // oh, h - 1)
        ix = jnp.minimum(jnp.arange(ow) * w // ow, w - 1)
        return x[:, iy][:, :, ix]
    if data_format in ("NWC", "NHWC", "NDHWC"):
        from .. import layout

        fmt = {"NWC": "NCW", "NHWC": "NCHW", "NDHWC": "NCDHW"}
        # the tensor is explicitly transposed to channel-first here, so
        # the recursion's declared NCHW must NOT re-resolve to NHWC
        with layout.declared_scope():
            y = interpolate(jnp.moveaxis(x, -1, 1), size, scale_factor,
                            mode, align_corners, align_mode,
                            fmt[data_format])
        return jnp.moveaxis(y, 1, -1)
    if x.ndim == 3:
        n, c, w = x.shape
        if size is not None:
            ow = size if isinstance(size, int) else tuple(size)[0]
        else:
            sf = scale_factor if not isinstance(
                scale_factor, (tuple, list)) else scale_factor[0]
            ow = int(w * sf)
        if mode == "nearest":
            ix = jnp.minimum(jnp.arange(ow) * w // ow, w - 1)
            return x[:, :, ix]
        if mode == "linear":
            mx = _lin_weights(ow, w, align_corners, align_mode)
            return jnp.einsum("Ow,ncw->ncO", mx, x).astype(x.dtype)
        raise ValueError(f"interpolate 3-D: unknown mode {mode!r}")
    if x.ndim == 5:
        n, c, d, h, w = x.shape
        if size is not None:
            od, oh, ow = (size,) * 3 if isinstance(size, int) \
                else tuple(size)
        else:
            sf = (scale_factor,) * 3 if not isinstance(
                scale_factor, (tuple, list)) else scale_factor
            od, oh, ow = int(d * sf[0]), int(h * sf[1]), int(w * sf[2])
        if mode == "nearest":
            iz = jnp.minimum(jnp.arange(od) * d // od, d - 1)
            iy = jnp.minimum(jnp.arange(oh) * h // oh, h - 1)
            ix = jnp.minimum(jnp.arange(ow) * w // ow, w - 1)
            return x[:, :, iz][:, :, :, iy][:, :, :, :, ix]
        if mode == "trilinear":
            mz = _lin_weights(od, d, align_corners, align_mode)
            my = _lin_weights(oh, h, align_corners, align_mode)
            mx = _lin_weights(ow, w, align_corners, align_mode)
            return jnp.einsum(
                "Dd,Hh,Ww,ncdhw->ncDHW", mz, my, mx, x
            ).astype(x.dtype)
        raise ValueError(f"interpolate 5-D: unknown mode {mode!r}")
    n, c, h, w = x.shape
    if size is not None:
        oh, ow = (size, size) if isinstance(size, int) else tuple(size)
    else:
        sf = (scale_factor, scale_factor) if not isinstance(
            scale_factor, (tuple, list)) else scale_factor
        oh, ow = int(h * sf[0]), int(w * sf[1])
    if mode == "nearest":
        # paddle/torch nearest: floor(i * in/out)
        iy = jnp.minimum((jnp.arange(oh) * h // oh), h - 1)
        ix = jnp.minimum((jnp.arange(ow) * w // ow), w - 1)
        return x[:, :, iy][:, :, :, ix]
    if mode == "bilinear":
        my = _lin_weights(oh, h, align_corners, align_mode)
        mx = _lin_weights(ow, w, align_corners, align_mode)
        return jnp.einsum("Oh,nchw,Pw->ncOP", my, x, mx).astype(x.dtype)
    if mode == "bicubic":
        my = _cubic_weights(oh, h, align_corners)
        mx = _cubic_weights(ow, w, align_corners)
        return jnp.einsum("Oh,nchw,Pw->ncOP", my, x, mx).astype(x.dtype)
    if mode == "area":
        from .pooling import adaptive_avg_pool2d  # lazy: avoids cycle

        return adaptive_avg_pool2d(x, (oh, ow))
    raise ValueError(f"interpolate: unknown mode {mode!r}")


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW"):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (parity: F.unfold / the im2col phi kernel): x [N, C, H, W]
    -> [N, C*kh*kw, L] columns, torch/paddle channel-major (c, kh, kw)
    ordering. One lax.conv_general_dilated_patches call — XLA lowers it
    to the same window-gather the reference's CUDA kernel hand-writes."""
    x = _v(x)

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    pad = _pair(paddings)
    if len(pad) == 2:
        pads = [(pad[0], pad[0]), (pad[1], pad[1])]
    else:
        pads = [(pad[0], pad[1]), (pad[2], pad[3])]
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), pads, rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    n = x.shape[0]
    return patches.reshape(n, patches.shape[1], -1)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0,
         dilations=1, name=None):
    """col2im (parity: F.fold) — the exact linear transpose of
    ``unfold``, realized through jax.linear_transpose (overlapping
    windows scatter-add)."""
    x = _v(x)

    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    oh, ow = _pair(output_sizes)
    n, ckk, _ = x.shape
    kh, kw = _pair(kernel_sizes)
    c = ckk // (kh * kw)

    def _unfold_img(img):
        return unfold(img, kernel_sizes, strides, paddings, dilations)

    spec = jax.ShapeDtypeStruct((n, c, oh, ow), x.dtype)
    (out,) = jax.linear_transpose(_unfold_img, spec)(x)
    return out


def alpha_dropout(x, p=0.5, training=True, name=None):
    """SELU-preserving dropout (parity: F.alpha_dropout): dropped units
    take the negative-saturation value and an affine correction keeps
    mean/variance, so self-normalizing nets stay normalized."""
    x = _v(x)
    if not training or p == 0.0:
        return x
    if p == 1.0:
        return jnp.zeros_like(x)
    alpha_p = -1.7580993408473766  # -scale*alpha of SELU
    q = 1.0 - p
    a = (q + alpha_p * alpha_p * p * q) ** -0.5
    b = -a * alpha_p * p
    key = random_mod.next_rng_key("alpha_dropout")
    keep = jax.random.bernoulli(key, q, x.shape)
    return (a * jnp.where(keep, x, jnp.asarray(alpha_p, x.dtype))
            + jnp.asarray(b, x.dtype)).astype(x.dtype)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """Parity: F.zeropad2d — padding [left, right, top, bottom]."""
    x = _v(x)
    left, right, top, bottom = padding
    if data_format == "NCHW":
        width = [(0, 0), (0, 0), (top, bottom), (left, right)]
    else:
        width = [(0, 0), (top, bottom), (left, right), (0, 0)]
    return jnp.pad(x, width)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    """Whole-channel dropout for 5-D input (parity: F.dropout3d)."""
    x = _v(x)
    if not training or p == 0.0:
        return x
    key = random_mod.next_rng_key("dropout3d")
    shape = list(x.shape)
    if data_format == "NCDHW":
        shape[2] = shape[3] = shape[4] = 1
    else:
        shape[1] = shape[2] = shape[3] = 1
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)


def bilinear(x1, x2, weight, bias=None, name=None):
    """Parity: F.bilinear — out[b, o] = x1[b] @ W[o] @ x2[b] (+bias);
    weight [out, in1, in2]."""
    x1, x2, weight = _v(x1), _v(x2), _v(weight)
    y = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        y = y + _v(bias)
    return y


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    """Whole-channel dropout for 4-D input (parity: F.dropout2d)."""
    x = _v(x)
    if not training or p == 0.0:
        return x
    key = random_mod.next_rng_key("dropout2d")
    shape = list(x.shape)
    if data_format == "NCHW":
        shape[2] = shape[3] = 1
    else:
        shape[1] = shape[2] = 1
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False,
                      name=None):
    """Parity: F.pairwise_distance — ||x - y + eps||_p over the last
    axis (inf/-inf norms included)."""
    x, y = _v(x), _v(y)
    d = jnp.abs(x - y + epsilon)
    if p == float("inf"):
        return jnp.max(d, axis=-1, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(d, axis=-1, keepdims=keepdim)
    return jnp.sum(d ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)


def sequence_mask(lengths, maxlen=None, dtype="int64", name=None):
    """Parity: paddle.nn.functional.sequence_mask — [..., maxlen] mask
    of positions < length."""
    from ...core import dtype as dtype_mod

    lengths = _v(lengths)
    if maxlen is None:
        maxlen = int(jnp.max(lengths))
    pos = jnp.arange(maxlen)
    mask = pos[None, :] < lengths.reshape(-1, 1)
    mask = mask.reshape(*lengths.shape, maxlen)
    return mask.astype(dtype_mod.convert_dtype(dtype))


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """Parity: F.temporal_shift (TSM): within each segment of seg_num
    frames, the first shift_ratio of channels shifts one frame back,
    the next shift_ratio one frame forward, the rest stay."""
    x = _v(x)
    if data_format == "NHWC":
        return jnp.transpose(
            temporal_shift(jnp.transpose(x, (0, 3, 1, 2)), seg_num,
                           shift_ratio), (0, 2, 3, 1))
    nt, c, h, w = x.shape
    n = nt // seg_num
    x5 = x.reshape(n, seg_num, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    back = jnp.concatenate(
        [x5[:, 1:, :c1], jnp.zeros_like(x5[:, :1, :c1])], axis=1)
    fwd = jnp.concatenate(
        [jnp.zeros_like(x5[:, :1, c1:c2]), x5[:, :-1, c1:c2]], axis=1)
    out = jnp.concatenate([back, fwd, x5[:, :, c2:]], axis=2)
    return out.reshape(nt, c, h, w)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    """Parity: F.channel_shuffle (ShuffleNet)."""
    x = _v(x)
    if data_format == "NHWC":
        return jnp.transpose(
            channel_shuffle(jnp.transpose(x, (0, 3, 1, 2)), groups),
            (0, 2, 3, 1))
    n, c, h, w = x.shape
    x = x.reshape(n, groups, c // groups, h, w)
    x = jnp.swapaxes(x, 1, 2)
    return x.reshape(n, c, h, w)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    """Parity: F.label_smooth — (1-eps)*label + eps*prior (uniform by
    default over the last axis)."""
    label = _v(label)
    k = label.shape[-1]
    prior = (1.0 / k if prior_dist is None else _v(prior_dist))
    return (1.0 - epsilon) * label + epsilon * prior


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    """Parity: F.gumbel_softmax — differentiable categorical samples;
    ``hard`` straight-through one-hots."""
    x = _v(x)
    key = random_mod.next_rng_key("gumbel")
    g = jax.random.gumbel(key, x.shape, jnp.float32)
    y = jax.nn.softmax((x.astype(jnp.float32) + g) / temperature,
                       axis=axis)
    if hard:
        # straight-through: one-hot forward, soft gradients backward
        onehot = jax.nn.one_hot(jnp.argmax(y, axis=axis),
                                y.shape[axis], axis=axis, dtype=y.dtype)
        y = y + jax.lax.stop_gradient(onehot - y)
    return y.astype(x.dtype)
