"""Activation functional forms (parity: python/paddle/nn/functional/activation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import random as random_mod
from .common import _v


def relu(x):
    return jax.nn.relu(_v(x))


def relu6(x):
    return jax.nn.relu6(_v(x))


def gelu(x, approximate=False):
    return jax.nn.gelu(_v(x), approximate=approximate)


def silu(x):
    return jax.nn.silu(_v(x))


swish = silu


def sigmoid(x):
    return jax.nn.sigmoid(_v(x))


def tanh(x):
    return jnp.tanh(_v(x))


def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(_v(x), negative_slope)


def elu(x, alpha=1.0):
    return jax.nn.elu(_v(x), alpha)


def softplus(x, beta=1.0, threshold=20.0):
    return jax.nn.softplus(_v(x) * beta) / beta


def hardswish(x):
    return jax.nn.hard_swish(_v(x))


def hardsigmoid(x):
    x = _v(x)
    return jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


def mish(x):
    return jax.nn.mish(_v(x))


def softmax(x, axis=-1):
    return jax.nn.softmax(_v(x), axis=axis)


def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(_v(x), axis=axis)


def glu(x, axis=-1):
    return jax.nn.glu(_v(x), axis=axis)


def swiglu(x, y=None):
    """Parity: phi fusion swiglu — silu(x) * y (split x in half if y None)."""
    x = _v(x)
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * _v(y)


def log_sigmoid(x):
    return jax.nn.log_sigmoid(_v(x))


def softsign(x):
    return jax.nn.soft_sign(_v(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    # jax.nn.elu guards expm1 against overflow in the untaken branch
    # (bare where leaks NaN grads at large positive x)
    return scale * jax.nn.elu(_v(x), alpha)


def celu(x, alpha=1.0):
    return jax.nn.celu(_v(x), alpha)


def hardshrink(x, threshold=0.5):
    x = _v(x)
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def softshrink(x, threshold=0.5):
    x = _v(x)
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


def tanhshrink(x):
    x = _v(x)
    return x - jnp.tanh(x)


def hardtanh(x, min=-1.0, max=1.0):  # noqa: A002
    return jnp.clip(_v(x), min, max)


def thresholded_relu(x, threshold=1.0):
    x = _v(x)
    return jnp.where(x > threshold, x, 0.0)


def prelu(x, weight):
    """weight: scalar-shaped [1] or per-channel [C] (paddle NCHW
    channel-1 convention for >2-D inputs)."""
    x, w = _v(x), _v(weight)
    if w.size > 1 and x.ndim > 2:
        w = w.reshape((1, -1) + (1,) * (x.ndim - 2))
    return jnp.where(x > 0, x, w * x)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True,
          rng_key=None):
    """Randomized leaky ReLU: U[lower, upper] slope in training, the
    midpoint at inference (paddle semantics)."""
    x = _v(x)
    if not training:
        return jnp.where(x > 0, x, (lower + upper) / 2.0 * x)
    key = rng_key if rng_key is not None else \
        random_mod.next_rng_key("rrelu")
    slope = jax.random.uniform(key, x.shape, jnp.float32, lower, upper)
    return jnp.where(x > 0, x, slope.astype(x.dtype) * x)


def maxout(x, groups, axis=1):
    """Parity: paddle.nn.functional.maxout — max over ``groups``-sized
    channel blocks."""
    x = _v(x)
    axis = axis % x.ndim          # negative axis: normalize BEFORE the
    c = x.shape[axis]             # slice-splice below
    if c % groups:
        raise ValueError(f"maxout: channels {c} not divisible by "
                         f"groups {groups}")
    shape = list(x.shape)
    shape[axis: axis + 1] = [c // groups, groups]
    return jnp.max(x.reshape(shape), axis=axis + 1)
