"""Convolution functional forms (parity: python/paddle/nn/functional/conv.py)."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .. import layout
from .common import _v


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    """Weight layout [out_c, in_c/groups, k] (paddle convention)."""
    x, weight = _v(x), _v(weight)
    if isinstance(stride, int):
        stride = (stride,)
    if isinstance(dilation, int):
        dilation = (dilation,)
    if isinstance(padding, int):
        padding = [(padding, padding)]
    elif isinstance(padding, str):
        padding = padding.upper()
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCH", "OIH", "NCH") if data_format == "NCL" else
        ("NHC", "OIH", "NHC"),
    )
    y = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
    ).astype(x.dtype)
    if bias is not None:
        shape = (1, -1, 1) if data_format == "NCL" else (1, 1, -1)
        y = y + _v(bias).reshape(shape)
    return y


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    """Weight layout [out_c, in_c/groups, kh, kw] (paddle convention).

    bf16 convs run without ``preferred_element_type``: the TPU MXU
    accumulates bf16 partial products in fp32 natively, and requesting
    an f32 result breaks reverse-mode AD (the transpose rule feeds the
    f32 cotangent and bf16 weight into a gradient conv, and
    ``conv_general_dilated`` rejects mixed operand dtypes)."""
    x, weight = _v(x), _v(weight)
    data_format = layout.resolve(data_format)
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    elif isinstance(padding, str):
        padding = padding.upper()
    elif isinstance(padding, (list, tuple)) and len(padding) == 2 and all(
        isinstance(p, int) for p in padding
    ):
        padding = [(padding[0], padding[0]), (padding[1], padding[1])]
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "OIHW", "NHWC"),
    )
    y = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
    )
    y = y.astype(x.dtype)
    if bias is not None:
        b = _v(bias)
        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        y = y + b.reshape(shape)
    return y


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    """Weight layout [out_c, in_c/groups, kd, kh, kw]."""
    x, weight = _v(x), _v(weight)
    if isinstance(stride, int):
        stride = (stride,) * 3
    if isinstance(dilation, int):
        dilation = (dilation,) * 3
    if isinstance(padding, int):
        padding = [(padding, padding)] * 3
    elif isinstance(padding, str):
        padding = padding.upper()
    dn = lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCDHW", "OIDHW", "NCDHW") if data_format == "NCDHW" else
        ("NDHWC", "OIDHW", "NDHWC"),
    )
    y = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
    ).astype(x.dtype)
    if bias is not None:
        shape = (1, -1, 1, 1, 1) if data_format == "NCDHW" \
            else (1, 1, 1, 1, -1)
        y = y + _v(bias).reshape(shape)
    return y


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW"):
    """Gradient/fractionally-strided conv (parity: F.conv2d_transpose).
    Weight layout [in_c, out_c/groups, kh, kw] (paddle convention).
    Implemented as conv_general_dilated with lhs_dilation=stride — the
    exact transpose of the forward conv, which XLA maps to the MXU the
    same way."""
    x, weight = _v(x), _v(weight)
    data_format = layout.resolve(data_format)
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(output_padding, int):
        output_padding = (output_padding, output_padding)
    kh, kw = weight.shape[-2:]
    # transpose-conv padding: k - 1 - p on each side (+output_padding low)
    pads = []
    for (k, p, op, d) in ((kh, padding[0], output_padding[0], dilation[0]),
                          (kw, padding[1], output_padding[1], dilation[1])):
        eff_k = (k - 1) * d + 1
        pads.append((eff_k - 1 - p, eff_k - 1 - p + op))
    # weight [in, out/groups, kh, kw] → flip taps, swap to [out, in/groups]
    w = jnp.flip(weight, axis=(-2, -1))
    if groups == 1:
        w = jnp.swapaxes(w, 0, 1)  # [out, in, kh, kw]
    else:
        i, og, khw = weight.shape[0], weight.shape[1], weight.shape[2:]
        w = w.reshape(groups, i // groups, og, *khw)
        w = jnp.swapaxes(w, 1, 2).reshape(groups * og, i // groups, *khw)
    dn = lax.conv_dimension_numbers(
        x.shape, w.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else
        ("NHWC", "OIHW", "NHWC"),
    )
    y = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pads, lhs_dilation=stride,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
    ).astype(x.dtype)
    if bias is not None:
        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        y = y + _v(bias).reshape(shape)
    return y


def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, nd, channels_first, spec):
    """Shared N-D transpose conv (fractionally-strided): the 2-D form
    above, generalized. Weight [in_c, out_c/groups, *k]."""
    if isinstance(stride, int):
        stride = (stride,) * nd
    if isinstance(dilation, int):
        dilation = (dilation,) * nd
    if isinstance(padding, int):
        padding = (padding,) * nd
    if isinstance(output_padding, int):
        output_padding = (output_padding,) * nd
    ks = weight.shape[-nd:]
    pads = []
    for (k, p, op, d) in zip(ks, padding, output_padding, dilation):
        eff_k = (k - 1) * d + 1
        pads.append((eff_k - 1 - p, eff_k - 1 - p + op))
    w = jnp.flip(weight, axis=tuple(range(-nd, 0)))
    if groups == 1:
        w = jnp.swapaxes(w, 0, 1)
    else:
        i, og, khw = weight.shape[0], weight.shape[1], weight.shape[2:]
        w = w.reshape(groups, i // groups, og, *khw)
        w = jnp.swapaxes(w, 1, 2).reshape(groups * og, i // groups, *khw)
    dn = lax.conv_dimension_numbers(x.shape, w.shape, spec)
    y = lax.conv_general_dilated(
        x, w, window_strides=(1,) * nd, padding=pads, lhs_dilation=stride,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
    ).astype(x.dtype)
    if bias is not None:
        shape = [1] * y.ndim
        shape[1 if channels_first else -1] = -1
        y = y + _v(bias).reshape(shape)
    return y


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCL"):
    """Parity: F.conv1d_transpose; weight [in_c, out_c/groups, k]."""
    x, weight = _v(x), _v(weight)
    cf = data_format == "NCL"
    spec = ("NCH", "OIH", "NCH") if cf else ("NHC", "OIH", "NHC")
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, 1, cf,
                              spec)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCDHW"):
    """Parity: F.conv3d_transpose; weight [in_c, out_c/groups, kd, kh, kw]."""
    x, weight = _v(x), _v(weight)
    cf = data_format == "NCDHW"
    spec = (("NCDHW", "OIDHW", "NCDHW") if cf
            else ("NDHWC", "OIDHW", "NDHWC"))
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups, 3, cf,
                              spec)
