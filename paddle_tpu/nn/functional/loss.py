"""Loss functional forms (parity: python/paddle/nn/functional/loss.py; ctc_loss replaces the vendored warpctc
with a lax.scan log-semiring recursion)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .common import _f32up, _v, cosine_similarity


def cross_entropy(
    logits,
    label,
    soft_label: bool = False,
    ignore_index: int = -100,
    reduction: str = "mean",
    axis: int = -1,
    label_smoothing: float = 0.0,
):
    """Parity: F.cross_entropy (softmax_with_cross_entropy phi kernel).

    Computes in fp32 regardless of input dtype (matching the fused kernel's
    accumulation behavior).
    """
    logits = _f32up(_v(logits))
    if axis not in (-1, logits.ndim - 1):
        # normalize to class-dim-last so gathers/one-hots line up
        logits = jnp.moveaxis(logits, axis, -1)
        if soft_label:
            label = jnp.moveaxis(_v(label), axis, -1)
        axis = -1
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        target = _v(label).astype(logits.dtype)
        loss = -jnp.sum(target * logp, axis=axis)
        valid = jnp.ones(loss.shape, jnp.float32)
    else:
        label = _v(label)
        num_classes = logits.shape[axis]
        if label_smoothing > 0.0:
            onehot = jax.nn.one_hot(label, num_classes, dtype=jnp.float32)
            smooth = (
                onehot * (1.0 - label_smoothing) + label_smoothing / num_classes
            )
            loss = -jnp.sum(smooth * logp, axis=axis)
        else:
            safe_label = jnp.where(label == ignore_index, 0, label)
            loss = -jnp.take_along_axis(
                logp, safe_label[..., None], axis=axis
            ).squeeze(axis)
        valid = (label != ignore_index).astype(jnp.float32)
        loss = loss * valid
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    denom = jnp.maximum(jnp.sum(valid), 1.0)
    return jnp.sum(loss) / denom


def mse_loss(input, label, reduction="mean"):  # noqa: A002
    d = (_v(input) - _v(label)) ** 2
    if reduction == "none":
        return d
    return jnp.sum(d) if reduction == "sum" else jnp.mean(d)


def l1_loss(input, label, reduction="mean"):  # noqa: A002
    d = jnp.abs(_v(input) - _v(label))
    if reduction == "none":
        return d
    return jnp.sum(d) if reduction == "sum" else jnp.mean(d)


def nll_loss(log_probs, label, reduction="mean", ignore_index=-100):
    logp = _v(log_probs)
    label = _v(label)
    safe = jnp.where(label == ignore_index, 0, label)
    loss = -jnp.take_along_axis(logp, safe[..., None], axis=-1).squeeze(-1)
    valid = (label != ignore_index).astype(loss.dtype)
    loss = loss * valid
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1.0)


def binary_cross_entropy_with_logits(logits, label, reduction="mean"):
    logits = _f32up(_v(logits))
    label = _v(label).astype(logits.dtype)
    loss = jnp.maximum(logits, 0) - logits * label + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    if reduction == "none":
        return loss
    return jnp.sum(loss) if reduction == "sum" else jnp.mean(loss)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Connectionist Temporal Classification loss.

    Parity: paddle.nn.functional.ctc_loss (reference: the warpctc op,
    paddle/phi/kernels/impl/warpctc_kernel_impl.h, built from the vendored
    third_party warpctc — SURVEY §2.3). ``log_probs`` are UNNORMALIZED
    logits of shape [max_time, batch, num_classes]; softmax is applied
    internally, matching warpctc.

    TPU design: warpctc's hand-scheduled CUDA alpha/beta kernels become a
    single ``lax.scan`` over time of the log-semiring alpha recursion on
    the extended (blank-interleaved) label sequence — static shapes,
    batch-vectorized, masked for variable time/label lengths. The backward
    pass is jax autodiff through the scan, which reproduces the classic
    beta-recursion gradient without a hand-written kernel.
    """
    lp = jax.nn.log_softmax(_f32up(_v(log_probs)), axis=-1)
    labels = _v(labels)
    input_lengths = jnp.asarray(input_lengths, jnp.int32)
    label_lengths = jnp.asarray(label_lengths, jnp.int32)
    T, B, C = lp.shape
    L = labels.shape[1]
    S = 2 * L + 1
    neg_inf = jnp.asarray(-1e30, lp.dtype)

    # extended sequence: [blank, l0, blank, l1, ..., blank]
    s_idx = jnp.arange(S)
    lab_pos = jnp.clip((s_idx - 1) // 2, 0, L - 1)
    is_label = (s_idx % 2) == 1
    ext = jnp.where(is_label[None, :], labels[:, lab_pos], blank)  # [B, S]

    # skip transition s-2 -> s allowed iff ext[s] is a label differing
    # from ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=blank)[:, :S]
    skip_ok = is_label[None, :] & (ext != ext_m2) & (s_idx[None, :] >= 2)

    # per-step emission log-probs for every extended position: [T, B, S]
    emit = jnp.take_along_axis(
        lp, jnp.broadcast_to(ext[None], (T, B, S)), axis=2
    )

    alpha0 = jnp.full((B, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(emit[0, :, 0])
    if S > 1:
        # first label only reachable if the sequence is non-empty
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(label_lengths > 0, emit[0, :, 1], neg_inf)
        )

    def _shift(a, k):
        return jnp.pad(a, ((0, 0), (k, 0)), constant_values=neg_inf)[:, :S]

    def step(alpha, xs):
        emit_t, t = xs
        a1 = alpha
        a2 = _shift(alpha, 1)
        a3 = jnp.where(skip_ok, _shift(alpha, 2), neg_inf)
        stacked = jnp.stack([a1, a2, a3])
        m = jnp.max(stacked, axis=0)
        new = m + jnp.log(
            jnp.sum(jnp.exp(stacked - m[None]), axis=0)
        ) + emit_t
        # freeze alpha once past each sequence's input length
        alpha = jnp.where((t < input_lengths)[:, None], new, alpha)
        return alpha, None

    alpha, _ = lax.scan(step, alpha0, (emit[1:], jnp.arange(1, T)))

    last = 2 * label_lengths  # final blank position in the extended seq
    a_last = jnp.take_along_axis(alpha, last[:, None], axis=1)[:, 0]
    a_prev = jnp.where(
        label_lengths > 0,
        jnp.take_along_axis(
            alpha, jnp.maximum(last - 1, 0)[:, None], axis=1
        )[:, 0],
        neg_inf,
    )
    m = jnp.maximum(a_last, a_prev)
    ll = m + jnp.log(jnp.exp(a_last - m) + jnp.exp(a_prev - m))
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(input_lengths, 1).astype(loss.dtype)
    if reduction == "mean":
        # paddle: divide each loss by its label length, then mean
        return jnp.mean(
            loss / jnp.maximum(label_lengths, 1).astype(loss.dtype)
        )
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean"):
    """RNN-T (transducer) loss.

    Parity: paddle.nn.functional.rnnt_loss (reference: the warprnnt op
    over the vendored third_party warp_transducer — SURVEY §2.3).
    ``input``: [B, T, U+1, V] unnormalized joint-network logits
    (log_softmax applied internally, matching warprnnt); ``label``:
    [B, U] int; per-sample ``input_lengths`` / ``label_lengths``.

    TPU design: the (t, u) lattice DP is ONE ``lax.scan`` over t. The
    in-row recurrence alpha[t,u] = logaddexp(alpha[t-1,u] + blank,
    alpha[t,u-1] + emit) is solved in CLOSED FORM per row: with
    G_u = prefix-sum of emit scores, x_u = G_u + cumlogsumexp(c - G)_u
    — no per-u python/scan loop, fully batch-vectorized, static shapes.
    FastEmit regularization uses warprnnt's exact semantics (emit-arc
    gradients scaled by 1+lambda) via a value-preserving
    ``stop_gradient`` reparameterization of the emit scores; the loss
    VALUE is identical to lambda=0, only gradients change. Backward is
    autodiff through the scan (the beta recursion, synthesized).
    """
    lp = jax.nn.log_softmax(_f32up(_v(input)), axis=-1)
    label = _v(label).astype(jnp.int32)
    input_lengths = jnp.asarray(input_lengths, jnp.int32)
    label_lengths = jnp.asarray(label_lengths, jnp.int32)
    B, T, U1, V = lp.shape
    U = U1 - 1
    if label.shape[1] != U:
        raise ValueError(
            f"label width {label.shape[1]} must equal input's U axis - 1 "
            f"= {U} (input is [B, T, U+1, V])")
    neg_inf = jnp.asarray(-1e30, lp.dtype)

    blank_lp = lp[..., blank]  # [B, T, U+1]
    if U > 0:
        emit_lp = jnp.take_along_axis(
            lp[:, :, :U, :],
            jnp.broadcast_to(label[:, None, :, None], (B, T, U, 1)),
            axis=3,
        )[..., 0]  # [B, T, U]
        # tokens past each sample's label length cannot be emitted
        emit_lp = jnp.where(
            (jnp.arange(U)[None, :] < label_lengths[:, None])[:, None, :],
            emit_lp, neg_inf)
        if fastemit_lambda:
            # d(loss)/d(emit) scales by (1+lambda); forward value exact
            lam = float(fastemit_lambda)
            emit_lp = (emit_lp * (1.0 + lam)
                       - lax.stop_gradient(emit_lp * lam))
    else:
        emit_lp = jnp.zeros((B, T, 0), lp.dtype)

    def row_prefix(e_t):
        # G[u] = sum of emit scores before u: [B, U+1], G[0] = 0
        return jnp.concatenate(
            [jnp.zeros((B, 1), lp.dtype), jnp.cumsum(e_t, axis=1)], axis=1)

    # t = 0: alpha[0, u] = emit-only prefix
    alpha0 = row_prefix(emit_lp[:, 0])

    def step(alpha_prev, xs):
        b_prev, e_t = xs  # blank row t-1, emit row t
        c = alpha_prev + b_prev
        G = row_prefix(e_t)
        alpha_t = G + lax.cumlogsumexp(c - G, axis=1)
        # keep lattice garbage (masked regions) finite, never NaN
        alpha_t = jnp.maximum(alpha_t, neg_inf)
        return alpha_t, alpha_t

    if T > 1:
        xs = (jnp.moveaxis(blank_lp[:, :-1], 1, 0),
              jnp.moveaxis(emit_lp[:, 1:], 1, 0))
        _, rows = lax.scan(step, alpha0, xs)
        alpha = jnp.concatenate([alpha0[None], rows], axis=0)  # [T,B,U+1]
    else:
        alpha = alpha0[None]

    # log Z = alpha[T_b-1, U_b] + blank[T_b-1, U_b]
    t_last = jnp.maximum(input_lengths - 1, 0)
    a_tb = jnp.take_along_axis(
        jnp.moveaxis(alpha, 0, 1), t_last[:, None, None],
        axis=1)[:, 0]  # [B, U+1]
    a_final = jnp.take_along_axis(
        a_tb, jnp.minimum(label_lengths, U)[:, None], axis=1)[:, 0]
    b_final = jnp.take_along_axis(
        jnp.take_along_axis(
            blank_lp, t_last[:, None, None], axis=1)[:, 0],
        jnp.minimum(label_lengths, U)[:, None], axis=1)[:, 0]
    loss = -(a_final + b_final)
    # paddle/warprnnt: plain mean over the batch
    return _reduce_loss(loss, reduction)


def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def kl_div(input, label, reduction="mean"):  # noqa: A002
    """input is LOG-probabilities (paddle convention)."""
    x, t = _v(input), _v(label)
    loss = t * (jnp.log(jnp.clip(t, 1e-30)) - x)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    return _reduce_loss(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0,
                        reduction="mean"):  # noqa: A002
    loss = jnp.maximum(
        0.0, -_v(label) * (_v(input) - _v(other)) + margin)
    return _reduce_loss(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):  # noqa: A002
    d = jnp.abs(_v(input) - _v(label))
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce_loss(loss, reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False,
                        reduction="mean"):  # noqa: A002
    def dist(a, b):
        return jnp.power(
            jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p), axis=-1),
            1.0 / p)

    a, pos, neg = _v(input), _v(positive), _v(negative)
    d_pos = dist(a, pos)
    d_neg = dist(a, neg)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(pos, neg))
    return _reduce_loss(jnp.maximum(0.0, d_pos - d_neg + margin),
                        reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean"):
    x1, x2 = _v(input1), _v(input2)
    if x1.ndim == 1:      # paddle accepts a single [M] pair
        x1, x2 = x1[None], x2[None]
    cos = cosine_similarity(x1, x2, axis=1)
    loss = jnp.where(_v(label) > 0, 1.0 - cos,
                     jnp.maximum(0.0, cos - margin))
    return _reduce_loss(loss, reduction)


def soft_margin_loss(input, label, reduction="mean"):  # noqa: A002
    return _reduce_loss(jax.nn.softplus(-_v(label) * _v(input)),
                        reduction)


def hinge_embedding_loss(input, label, margin=1.0,
                         reduction="mean"):  # noqa: A002
    x = _v(input)
    loss = jnp.where(_v(label) > 0, x, jnp.maximum(0.0, margin - x))
    return _reduce_loss(loss, reduction)


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean"):  # noqa: A002
    x, t = _v(input), _v(label)
    if log_input:
        loss = jnp.exp(x) - t * x
    else:
        loss = x - t * jnp.log(x + epsilon)
    if full:
        stirling = (t * jnp.log(t) - t
                    + 0.5 * jnp.log(2.0 * jnp.pi * t))
        loss = loss + jnp.where(t > 1, stirling, 0.0)
    return _reduce_loss(loss, reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):  # noqa: A002
    var = jnp.maximum(_v(variance), epsilon)
    loss = 0.5 * (jnp.log(var) + jnp.square(_v(input) - _v(label)) / var)
    if full:
        loss = loss + 0.5 * jnp.log(jnp.asarray(2.0 * jnp.pi))
    return _reduce_loss(loss, reduction)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean"):  # noqa: A002
    x, t = _v(input), _v(label)
    loss = -(t * jax.nn.log_sigmoid(x)
             + (1 - t) * jax.nn.log_sigmoid(-x))
    if weight is not None:
        loss = loss * _v(weight)
    return _reduce_loss(jnp.mean(loss, axis=-1), reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0, reduction="sum"):
    """Parity: paddle.nn.functional.sigmoid_focal_loss (RetinaNet)."""
    x, t = _f32up(_v(logit)), _v(label).astype(jnp.float32)
    p = jax.nn.sigmoid(x)
    ce = -(t * jax.nn.log_sigmoid(x) + (1 - t) * jax.nn.log_sigmoid(-x))
    p_t = p * t + (1 - p) * (1 - t)
    a_t = alpha * t + (1 - alpha) * (1 - t)
    loss = a_t * jnp.power(1 - p_t, gamma) * ce
    if normalizer is not None:
        loss = loss / _v(normalizer)
    return _reduce_loss(loss, reduction)


def dice_loss(input, label, epsilon=1e-5):  # noqa: A002
    """Parity: paddle.nn.functional.dice_loss — input [N, ..., C]
    probabilities, label [N, ..., 1] class ids."""
    x = _v(input)
    t = jax.nn.one_hot(jnp.squeeze(_v(label), -1), x.shape[-1],
                       dtype=x.dtype)
    reduce_dims = tuple(range(1, x.ndim))
    inter = jnp.sum(x * t, axis=reduce_dims)
    union = jnp.sum(x, axis=reduce_dims) + jnp.sum(t, axis=reduce_dims)
    return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))


def log_loss(input, label, epsilon=1e-4):  # noqa: A002
    """Parity: paddle.nn.functional.log_loss (probability input)."""
    x, t = _v(input), _v(label)
    return -(t * jnp.log(x + epsilon)
             + (1 - t) * jnp.log(1 - x + epsilon))


def square_error_cost(input, label):  # noqa: A002
    return jnp.square(_v(input) - _v(label))


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    """Parity: F.binary_cross_entropy — input are PROBABILITIES (the
    post-sigmoid form; see binary_cross_entropy_with_logits for
    logits)."""
    p = _f32up(_v(input))
    y = _v(label).astype(p.dtype)
    eps = 1e-12
    loss = -(y * jnp.log(jnp.maximum(p, eps))
             + (1.0 - y) * jnp.log(jnp.maximum(1.0 - p, eps)))
    if weight is not None:
        loss = loss * _v(weight).astype(loss.dtype)
    if reduction == "none":
        return loss
    return jnp.sum(loss) if reduction == "sum" else jnp.mean(loss)
