"""Attention functional forms (parity: python/paddle/nn/functional/flash_attention.py)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .common import _f32up, _v, dropout


def scaled_dot_product_attention(
    query,
    key,
    value,
    attn_mask=None,
    dropout_p: float = 0.0,
    is_causal: bool = False,
    scale: Optional[float] = None,
    training: bool = True,
):
    """Reference attention in pure XLA. Layout: [batch, seq, heads, dim]
    (paddle flash_attention layout, phi flash_attn kernel).

    The Pallas flash-attention kernel (paddle_tpu.kernels.flash_attention)
    is preferred on TPU for long sequences; this is the numerics reference
    and the general fallback (arbitrary masks, GQA).
    """
    q, k, v = _v(query), _v(key), _v(value)
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    if hq != hk:  # grouped-query attention: repeat kv heads
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = scale if scale is not None else d ** -0.5
    # [b, h, sq, sk]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = _f32up(logits)
    if is_causal:
        sk = k.shape[1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(causal, logits, jnp.float32(-1e30))
    if attn_mask is not None:
        m = _v(attn_mask)
        if m.dtype == jnp.bool_:
            logits = jnp.where(m, logits, jnp.float32(-1e30))
        else:
            logits = logits + m.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        probs = dropout(probs, dropout_p, training=True)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def flash_attention(
    query, key, value, dropout=0.0, causal=False, *, training=True, **kw
):
    """Parity: paddle.nn.functional.flash_attention.flash_attention.

    Dispatches to the Pallas TPU kernel when running on TPU with supported
    shapes, else the XLA reference path.
    """
    from ...kernels import flash_attention as fa

    return fa.flash_attention(
        _v(query), _v(key), _v(value), causal=causal,
        dropout_p=dropout, training=training,
    )
