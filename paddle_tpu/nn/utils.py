"""paddle_tpu.nn.utils (parity: python/paddle/nn/utils/ — weight_norm,
remove_weight_norm, spectral_norm, clip_grad_norm_, clip_grad_value_,
parameters_to_vector, vector_to_parameters).

Reparameterization design in a functional world: ``weight_norm`` replaces
the layer's ``weight`` Parameter with ``weight_g``/``weight_v`` Parameters
and recomputes the plain-array ``weight`` attribute inside a forward
pre-hook. Because the recompute reads the (possibly tracer-swapped)
Parameter values, the same layer works eagerly AND under
``functional_call``/jit/grad — gradients flow to g and v, which is the
whole point of the reparameterization.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.module import Layer
from ..core.parameter import Parameter


def _norm_except_dim(v, dim):
    """L2 norm over all axes except ``dim`` (paddle weight_norm layout);
    dim=None → full-tensor norm (scalar g)."""
    if dim is None:
        return jnp.sqrt(jnp.sum(jnp.square(v)))
    dim = dim % v.ndim
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))


def weight_norm(layer: Layer, name: str = "weight", dim: int = 0):
    """Parity: paddle.nn.utils.weight_norm — w = g · v / ||v||."""
    if name not in layer._parameters:
        raise ValueError(f"weight_norm: no parameter {name!r}")
    w = layer._parameters.pop(name)
    g0 = _norm_except_dim(w.value, dim)
    layer.add_parameter(f"{name}_g", Parameter(g0, name=f"{w.name}_g"))
    layer.add_parameter(f"{name}_v",
                        Parameter(w.value, name=f"{w.name}_v"))

    def _recompute(lyr, inputs):
        g = lyr._parameters[f"{name}_g"].value
        v = lyr._parameters[f"{name}_v"].value
        # plain-array attribute: functional extraction sees only g and v
        object.__setattr__(
            lyr, name, v * (g / _norm_except_dim(v, dim)))
        return inputs

    handle = layer.register_forward_pre_hook(_recompute)
    layer.__dict__.setdefault("_weight_norm_hooks", {})[name] = (
        handle, dim)
    _recompute(layer, ())
    return layer


def remove_weight_norm(layer: Layer, name: str = "weight"):
    """Fold g·v/||v|| back into a single Parameter."""
    hooks = layer.__dict__.get("_weight_norm_hooks", {})
    if name not in hooks:
        raise ValueError(f"remove_weight_norm: {name!r} not weight-normed")
    handle, dim = hooks.pop(name)
    handle.remove()
    g = layer._parameters.pop(f"{name}_g")
    v = layer._parameters.pop(f"{name}_v")
    w = v.value * (g.value / _norm_except_dim(v.value, dim))
    layer.__dict__.pop(name, None)
    layer.add_parameter(name, Parameter(w, name=v.name[:-2]))
    return layer


def spectral_norm(layer: Layer, name: str = "weight",
                  n_power_iterations: int = 1, eps: float = 1e-12,
                  dim: int = 0):
    """Parity: paddle.nn.utils.spectral_norm — w / sigma_max(w), with the
    power-iteration vector ``u`` kept as a buffer. Under jit the
    iteration runs from the stored buffer (stop-gradient, reference
    behavior); the buffer itself advances on eager calls."""
    if name not in layer._parameters:
        raise ValueError(f"spectral_norm: no parameter {name!r}")
    w = layer._parameters.pop(name)
    layer.add_parameter(f"{name}_orig",
                        Parameter(w.value, name=f"{w.name}_orig"))
    mat0 = _to_matrix(w.value, dim)
    key = jax.random.PRNGKey(0)
    u0 = jax.random.normal(key, (mat0.shape[0],), jnp.float32)
    layer.register_buffer(f"{name}_u", u0 / jnp.linalg.norm(u0))

    def _recompute(lyr, inputs):
        wv = lyr._parameters[f"{name}_orig"].value
        mat = _to_matrix(wv, dim)
        u = lyr._buffers[f"{name}_u"]
        for _ in range(max(1, n_power_iterations)):
            v = mat.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = mat @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        u = jax.lax.stop_gradient(u)
        v = jax.lax.stop_gradient(v)
        sigma = u @ (mat @ v)
        object.__setattr__(lyr, name, wv / sigma)
        try:  # persist the iterate when running eagerly
            import jax.core as _jc

            if not isinstance(u, _jc.Tracer):
                lyr._buffers[f"{name}_u"] = u
        except Exception:
            pass
        return inputs

    handle = layer.register_forward_pre_hook(_recompute)
    layer.__dict__.setdefault("_spectral_norm_hooks", {})[name] = (
        handle, dim)
    _recompute(layer, ())
    return layer


def _to_matrix(w, dim):
    if dim != 0:
        w = jnp.moveaxis(w, dim, 0)
    return w.reshape(w.shape[0], -1)


# ---------------------------------------------------------------------------
# gradient / parameter vector utilities
# ---------------------------------------------------------------------------
def clip_grad_norm_(parameters, max_norm, norm_type=2.0):
    """Parity: paddle.nn.utils.clip_grad_norm_ — clips the ``.grad``
    fields in place, returns the total norm."""
    params = [p for p in parameters if getattr(p, "grad", None) is not None]
    if not params:
        return jnp.zeros(())
    if norm_type == float("inf"):
        total = jnp.max(jnp.asarray(
            [jnp.max(jnp.abs(p.grad)) for p in params]))
    else:
        total = jnp.sum(jnp.asarray(
            [jnp.sum(jnp.abs(p.grad) ** norm_type) for p in params]
        )) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in params:
        p.grad = p.grad * scale
    return total


def clip_grad_value_(parameters, clip_value):
    for p in parameters:
        if getattr(p, "grad", None) is not None:
            p.grad = jnp.clip(p.grad, -clip_value, clip_value)


def parameters_to_vector(parameters):
    return jnp.concatenate([jnp.ravel(p.value) for p in parameters])


def vector_to_parameters(vec, parameters):
    i = 0
    for p in parameters:
        n = int(jnp.size(p.value))
        p.value = vec[i:i + n].reshape(p.value.shape).astype(p.value.dtype)
        i += n
