"""Channels-last (NHWC) internal layout policy for conv workloads.

TPU convolutions are native channels-last: the MXU contracts over the
input-channel dimension, and XLA lays NHWC activations out with C on the
128-wide lane dimension — NCHW inputs force a relayout copy in front of
(and behind) every conv/norm/pool. The round-5 SD-UNet capture measured
exactly that: 40% of device time in {1,0,3,2}<->{0,1,3,2} copies.

This module keeps the paddle-facing convention (NCHW at every public
API boundary) while letting a MODEL hoist the layout change to its
entry/exit: the model transposes once, opens a ``channels_last_scope``,
and every conv/pool/norm functional inside resolves its declared
"NCHW" format to "NHWC" — the tensors flowing through them really are
channels-last, and no per-op transposes exist for XLA to clean up.

Policy resolution order (per model forward):
1. explicit per-model setting (``UNetConfig.channels_last``,
   ``ResNet(channels_last=...)``) when not None;
2. the ``PT_FLAGS_conv_layout`` flag / ``paddle_tpu.set_flags``:
   "NHWC" forces on, "NCHW" forces off;
3. "auto" (default): NHWC on TPU, NCHW elsewhere (CPU tests keep the
   reference layout bit-for-bit).

The scope is trace-time state: it is entered inside the model's
``forward`` while jit tracing, so the resolved layout is baked into the
compiled program (no runtime branching).
"""

from __future__ import annotations

import contextlib

from .. import flags

flags.define_flag(
    "conv_layout", "auto",
    "internal conv/pool/norm layout: NHWC | NCHW | auto (NHWC on TPU)")

# trace-time nesting depth of channels_last_scope; tracing is
# single-threaded per program, so a module-level counter suffices
_scope_depth = 0

# declared channels-first formats a scope retargets to channels-last
_CHANNELS_LAST_OF = {"NCHW": "NHWC"}


def channels_last_preferred() -> bool:
    """The env/flag policy (no per-model override applied)."""
    v = str(flags.flag("conv_layout")).upper()
    if v == "NHWC":
        return True
    if v == "NCHW":
        return False
    import jax

    return jax.default_backend() == "tpu"


def decide(explicit=None) -> bool:
    """Per-model policy: explicit setting wins, else the flag/auto."""
    if explicit is not None:
        return bool(explicit)
    return channels_last_preferred()


def active() -> bool:
    return _scope_depth > 0


@contextlib.contextmanager
def channels_last_scope(enabled: bool = True):
    """While open (and ``enabled``), 4-D ops declared NCHW resolve to
    NHWC — the model has already transposed its activations."""
    global _scope_depth
    if not enabled:
        yield False
        return
    _scope_depth += 1
    try:
        yield True
    finally:
        _scope_depth -= 1


def resolve(declared: str) -> str:
    """Map a layer's declared data_format to the format of the tensors
    actually flowing through it. Idempotent outside a scope and for
    formats that are already channels-last."""
    if _scope_depth > 0:
        return _CHANNELS_LAST_OF.get(declared, declared)
    return declared


@contextlib.contextmanager
def declared_scope():
    """Temporarily suspend scope resolution: inner calls see their
    declared data_format verbatim. Required when an op's NHWC branch
    transposes explicitly and recurses into its own NCHW
    implementation — without this, ``resolve`` re-maps the recursion's
    declared NCHW back to NHWC forever (RecursionError)."""
    global _scope_depth
    prev = _scope_depth
    _scope_depth = 0
    try:
        yield
    finally:
        _scope_depth = prev


def nchw_to_nhwc(x):
    import jax.numpy as jnp

    return jnp.transpose(x, (0, 2, 3, 1))


def nhwc_to_nchw(x):
    import jax.numpy as jnp

    return jnp.transpose(x, (0, 3, 1, 2))
