"""paddle_tpu.nn.initializer (parity: paddle.nn.initializer) — the
initializer zoo lives in core.initializer; this module is the public
namespace."""

from ..core.initializer import (  # noqa: F401
    Assign,
    Bilinear,
    Constant,
    Dirac,
    Initializer,
    KaimingNormal,
    KaimingUniform,
    Normal,
    Orthogonal,
    TruncatedNormal,
    Uniform,
    XavierNormal,
    XavierUniform,
    calculate_gain,
)
