"""Resilience layer for the serving engine: deterministic fault
injection + the graceful-degradation ladder.

Parity intent: upstream Paddle's production story leans on Fleet's
elastic fault tolerance (``paddle.distributed.launch`` restarts whole
workers). A TPU serving engine can do much better than a process
restart: every compiled program is functionally pure and every token
the engine has emitted lives host-side, so a failed step can be
QUARANTINED (its device effects discarded) and the affected requests
replayed bit-identically through the existing chunked-prefill program.
This module holds the two host-side pieces the engine composes:

* ``FaultInjector`` — a seeded, per-site RNG that makes chaos testing
  deterministic and CPU-runnable. Sites are the engine's dispatch
  seams (``step`` = dispatch exception, ``nan`` = NaN-logits storm,
  ``latency`` = stall before dispatch, ``pool`` = simulated KV-pool
  exhaustion at admission). Each site draws from its OWN
  ``numpy`` Generator stream, so enabling one site never perturbs
  another's schedule — two runs with the same spec and seed fire at
  exactly the same points.

* ``DegradationController`` — a small ladder state machine. Sustained
  admission saturation walks the level up to ``shed_batch`` →
  ``throttle`` (capacity causes get capacity remedies); repeated step
  faults in a sliding window jump straight to ``min_service``, which
  additionally disables speculative decoding and prefix-cache adoption
  (machinery failures get machinery remedies — and neither switch can
  change greedy outputs, only throughput). Good ticks walk the level
  back down one rung at a time.

Everything here is plain host bookkeeping: no device traffic, no
compiled programs, importable and testable without jax.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

# injection sites, in the order the engine consults them at a seam.
# APPEND-ONLY: each site's RNG stream is seeded on its index, so
# inserting a site would shift every later site's schedule and break
# seeded-chaos reproducibility across versions.
SITES = ("step", "nan", "latency", "pool",
         # state-corruption sites (sanitizer chaos): consulted once
         # per tick AFTER the step's host integration — a fire mangles
         # the engine's own bookkeeping (leaked page ref / desynced
         # scale pool / shrunk seq_len) so PT_FLAGS_sanitize runs can
         # prove the invariant checker catches real damage
         "leak_ref", "scale_desync", "seq_shrink",
         # replica-level sites (router chaos): consulted by the
         # multi-engine router's per-replica tick seam, never by the
         # engine itself — replica_crash kills the whole replica
         # (device state untrusted: slots reclaimed for cross-replica
         # failover, caches rebuilt), replica_hang stalls it (the
         # breaker opens on repeated no-progress health probes),
         # probe_flaky flips one health-probe verdict (a single flake
         # must NOT flap the breaker)
         "replica_crash", "replica_hang", "probe_flaky")

# the subset above that corrupts engine state instead of failing a
# dispatch (the engine's _corrupt_point consults exactly these)
CORRUPT_SITES = ("leak_ref", "scale_desync", "seq_shrink")

# the subset the multi-engine router consults at its per-replica tick
# seam (router.py); the engine never draws from these streams, so a
# fleet spec like "replica_crash:0.05" leaves every engine-level
# schedule untouched
ROUTER_SITES = ("replica_crash", "replica_hang", "probe_flaky")

# exception classes "auto" recovery treats as device/runtime failures
# (recoverable by quarantine + replay) as opposed to host logic bugs
# (which must propagate). XlaRuntimeError subclasses RuntimeError, so
# the isinstance check stays strict: a plain RuntimeError raised by
# scheduler code is NOT swallowed.
RUNTIME_ERRORS: tuple = ()
try:  # pragma: no cover - presence depends on the jaxlib build
    from jaxlib.xla_extension import XlaRuntimeError as _XlaErr

    RUNTIME_ERRORS += (_XlaErr,)
except Exception:  # noqa: BLE001
    pass
try:  # pragma: no cover
    from jax.errors import JaxRuntimeError as _JaxErr

    if _JaxErr not in RUNTIME_ERRORS:
        RUNTIME_ERRORS += (_JaxErr,)
except Exception:  # noqa: BLE001
    pass


class InjectedFault(RuntimeError):
    """A fault fired by the :class:`FaultInjector` at a dispatch seam.

    Raised BEFORE the compiled call dispatches, so the device cache
    state is untouched — recovery can requeue the step's requests
    without rebuilding the pools. ``site`` is the injection site
    (``"step"`` | ``"nan"``); ``program`` names the seam it fired at.
    """

    def __init__(self, site: str, program: str = ""):
        self.site = site
        self.program = program
        super().__init__(
            f"injected {site!r} fault at {program or 'dispatch'}")


class FaultInjector:
    """Seeded per-site fault schedule for chaos tests.

    ``spec`` is the ``PT_FLAGS_fault_inject`` string: comma-separated
    ``site:rate`` entries plus optional ``seed:<int>`` /
    ``latency_ms:<float>``, e.g. ``"step:0.1,nan:0.05,seed:7"``.
    Rates are per-consultation probabilities in ``[0, 1]``; a site
    with rate 0 never draws, so adding a site to the spec never shifts
    another site's stream.
    """

    def __init__(self, spec: str = "", seed: int = 0,
                 latency_ms: float = 25.0,
                 rates: Optional[Dict[str, float]] = None):
        self.rates = {s: 0.0 for s in SITES}
        self.seed = int(seed)
        self.latency_ms = float(latency_ms)
        if rates:
            for site, rate in rates.items():
                self._set_rate(site, rate)
        for part in filter(None,
                           (p.strip() for p in str(spec).split(","))):
            key, sep, val = part.partition(":")
            key = key.strip().lower()
            if not sep:
                raise ValueError(
                    f"fault_inject entry {part!r} is not 'key:value'")
            if key == "seed":
                self.seed = int(val)
            elif key == "latency_ms":
                self.latency_ms = float(val)
                if self.latency_ms <= 0:
                    raise ValueError(
                        f"latency_ms must be > 0; got {val}")
            else:
                self._set_rate(key, val)
        # independent, seed-derived stream per site: deterministic and
        # mutually isolated (numpy seeds on the whole tuple)
        self._rngs = {
            s: np.random.default_rng((0x5EED, self.seed, i))
            for i, s in enumerate(SITES)
        }
        self.draws = {s: 0 for s in SITES}
        self.fires = {s: 0 for s in SITES}

    def _set_rate(self, site: str, rate):
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r}; valid sites: {SITES} "
                f"(plus seed:<int>, latency_ms:<float>)")
        r = float(rate)
        if not 0.0 <= r <= 1.0:
            raise ValueError(
                f"fault rate for {site!r} must be in [0, 1]; got {r}")
        self.rates[site] = r

    @classmethod
    def from_flag(cls) -> Optional["FaultInjector"]:
        """Build from ``PT_FLAGS_fault_inject``; None when the flag is
        empty (the production default — zero overhead)."""
        from .. import flags

        spec = str(flags.flag("fault_inject")).strip()
        return cls(spec) if spec else None

    @property
    def enabled(self) -> bool:
        return any(r > 0 for r in self.rates.values())

    def fire(self, site: str) -> bool:
        """One consultation of ``site``'s schedule. Deterministic:
        the k-th call for a given (seed, site) always returns the same
        verdict, regardless of what other sites are configured."""
        rate = self.rates[site]
        if rate <= 0.0:
            return False
        self.draws[site] += 1
        hit = bool(self._rngs[site].random() < rate)
        if hit:
            self.fires[site] += 1
        return hit

    def snapshot(self) -> dict:
        # copy-on-read (ptlint CC001): the /healthz scrape thread reads
        # this through resilience_snapshot while the scheduler fires
        return {
            "enabled": self.enabled,
            "seed": self.seed,
            "latency_ms": self.latency_ms,
            "rates": {k: v for k, v in list(self.rates.items())},
            "draws": {k: v for k, v in list(self.draws.items())},
            "fires": {k: v for k, v in list(self.fires.items())},
        }


# degradation ladder levels, mildest first
LEVEL_NAMES = ("normal", "shed_batch", "throttle", "min_service")


class DegradationController:
    """Ladder state machine for graceful degradation.

    Called once per scheduler tick with the tick's health verdict.
    Escalation is cause-split:

    * **saturation** (requests waiting, no slot/pages to admit them)
      is a CAPACITY problem: ``trip_after`` consecutive saturated
      ticks climb one rung, capped at ``sat_max_level`` (default 2 =
      ``throttle``). Shedding batch-class admissions and throttling
      admission keeps interactive traffic alive; disabling correct
      machinery would not add capacity.
    * **faults** (quarantined steps, NaN storms) are a MACHINERY
      problem: ``fault_trip`` faults inside a sliding
      ``fault_window``-tick window jump straight to ``max_level``
      (``min_service``), which additionally switches off speculative
      decoding and prefix-cache adoption — the two subsystems whose
      failure modes ("repeated spec-verify failures", poisoned shared
      pages) the jump exists for. Neither switch changes greedy
      outputs, only throughput.

    ``recover_after`` consecutive healthy ticks walk one rung back
    down (never past a still-hot fault window), so recovery is
    deliberately slower than escalation.
    """

    def __init__(self, trip_after: int = 4, recover_after: int = 6,
                 fault_window: int = 32, fault_trip: int = 3,
                 sat_max_level: int = 2, max_level: int = 3):
        for name, v, lo in (("trip_after", trip_after, 1),
                            ("recover_after", recover_after, 1),
                            ("fault_window", fault_window, 1),
                            ("fault_trip", fault_trip, 1),
                            ("sat_max_level", sat_max_level, 0),
                            ("max_level", max_level, 0)):
            if int(v) < lo:
                raise ValueError(f"{name} must be >= {lo}; got {v}")
        if sat_max_level > max_level:
            raise ValueError("sat_max_level cannot exceed max_level")
        self.trip_after = int(trip_after)
        self.recover_after = int(recover_after)
        self.fault_window = int(fault_window)
        self.fault_trip = int(fault_trip)
        self.sat_max_level = int(sat_max_level)
        self.max_level = int(max_level)
        self.level = 0
        self._tick = 0
        self._sat_streak = 0
        self._good_streak = 0
        # both scrape-read structures are plain lists, NOT deques: the
        # scrape thread copies them via list(...) in snapshot(), and
        # list-of-list is atomic under the GIL while deque iteration
        # raises on concurrent append. _fault_log stays tiny (trimmed
        # to the sliding window each observe()), so del-from-front is
        # O(window), not a cost.
        self._fault_log: list = []  # (tick, count)
        self.transitions: list = []
        self._max_transitions = 64

    # ---------------- per-tick update ----------------
    def _window_faults(self) -> int:
        horizon = self._tick - self.fault_window
        while self._fault_log and self._fault_log[0][0] <= horizon:
            del self._fault_log[0]
        return sum(c for _, c in self._fault_log)

    def observe(self, *, saturated: bool, faults: int = 0) -> int:
        """One scheduler tick's health report; returns the new level."""
        self._tick += 1
        if faults > 0:
            self._fault_log.append((self._tick, int(faults)))
        wf = self._window_faults()
        if saturated:
            self._sat_streak += 1
        else:
            self._sat_streak = 0
        if not saturated and faults == 0:
            self._good_streak += 1
        else:
            self._good_streak = 0
        new = self.level
        if self._sat_streak >= self.trip_after and new < self.sat_max_level:
            new += 1
            self._sat_streak = 0
        if wf >= self.fault_trip and new < self.max_level:
            new = self.max_level
        if self._good_streak >= self.recover_after and new > 0 \
                and wf < self.fault_trip:
            new -= 1
            self._good_streak = 0
        if new != self.level:
            self.transitions.append({
                "tick": self._tick, "from": self.level, "to": new,
                "saturated": bool(saturated), "window_faults": wf,
            })
            if len(self.transitions) > self._max_transitions:
                del self.transitions[
                    :len(self.transitions) - self._max_transitions]
            self.level = new
        return new

    # ---------------- action bits ----------------
    @property
    def degraded(self) -> bool:
        return self.level > 0

    @property
    def shed_batch(self) -> bool:
        """Defer (never drop) batch-class admissions."""
        return self.level >= 1

    @property
    def throttle(self) -> bool:
        """Cap admission to one request per wave; ``step_adaptive``
        drops to its probe chunk (an already-compiled program — the
        ladder never triggers a new jit specialization)."""
        return self.level >= 2

    @property
    def disable_spec(self) -> bool:
        return self.level >= 3

    @property
    def disable_prefix(self) -> bool:
        return self.level >= 3

    @property
    def name(self) -> str:
        return LEVEL_NAMES[min(self.level, len(LEVEL_NAMES) - 1)]

    def snapshot(self) -> dict:
        # pure read (ptlint CC002): recount the fault window WITHOUT
        # the trim _window_faults performs — the scrape thread must
        # never mutate the scheduler-owned log, and the trim races
        # observe()'s own popleft
        horizon = self._tick - self.fault_window
        wf = sum(c for t, c in list(self._fault_log) if t > horizon)
        return {
            "enabled": True,
            "level": self.level,
            "name": self.name,
            "degraded": self.degraded,
            "shed_batch": self.shed_batch,
            "throttle": self.throttle,
            "disable_spec": self.disable_spec,
            "disable_prefix": self.disable_prefix,
            "sat_streak": self._sat_streak,
            "good_streak": self._good_streak,
            "window_faults": wf,
            "transitions": list(self.transitions),
        }
